// Recursive-descent Java parser producing JDT-shaped trees.
//
// Shape contract (mirrors what the reference pipeline observably depends on,
// /root/reference/Preprocess/get_ast_root_action.py + the 71-entry
// ast_change_vocab.json whose 65 AST labels are exactly the internal node
// kinds that may appear):
//   * every LEAF's label is the exact source token text, so the bridge's
//     ordered `codes.index(name)` scan (process_data_ast_parallel.py:157-168)
//     maps it to a diff-token position;
//   * NullLiteral and ThisExpression leaves carry NO label (the bridge
//     asserts this and substitutes 'null'/'this', get_ast_root_action.py:56-61);
//   * Names are leaves — a dotted chain `a.b.c` is ONE QualifiedName leaf with
//     the dotted label (never an internal node: 'qualifiedname' is absent from
//     the reference vocab, so the reference's GumTree produced only leaf
//     Names); dotted labels never match single diff tokens and are skipped by
//     the bridge, matching reference behavior;
//   * Modifier / PrimitiveType are leaves labelled with their token;
//   * Infix/Prefix/Postfix/Assignment nodes carry the operator as label
//     (internal-node labels only participate in diff Update actions);
//   * node.pos/length are char offsets into the source, pos == first
//     descendant token's offset (the bridge prunes wrapper-class nodes by
//     comparing pos against the fragment start, process_data_ast_parallel.py:143-146).
//
// Anything outside the supported grammar throws ParseError; callers degrade
// that chunk to code-tokens-only exactly like the reference does when its
// GumTree subprocess fails.
#include "astdiff.hpp"

#include <functional>

namespace astdiff {

namespace {

bool is_modifier(const std::string& s) {
  static const char* mods[] = {"public",    "protected", "private",  "static",
                               "abstract",  "final",     "native",   "synchronized",
                               "transient", "volatile",  "strictfp", "default"};
  for (const char* m : mods)
    if (s == m) return true;
  return false;
}

bool is_primitive(const std::string& s) {
  static const char* prims[] = {"boolean", "byte",  "char", "short",
                                "int",     "long",  "float", "double", "void"};
  for (const char* m : prims)
    if (s == m) return true;
  return false;
}

class Parser {
 public:
  explicit Parser(const std::string& src) : src_(src), toks_(lex(src)) {
    tree_ = std::make_unique<Tree>();
  }

  std::unique_ptr<Tree> run() {
    Node* cu = node("CompilationUnit");
    size_t s = mark();
    while (!at_end()) {
      if (at_op(";")) { advance(); continue; }
      size_t before = p_;
      if (at_kw("package")) {
        cu->children.push_back(parse_package());
      } else if (at_kw("import")) {
        cu->children.push_back(parse_import());
      } else {
        cu->children.push_back(parse_type_declaration());
      }
      if (p_ == before) err("parser made no progress");
    }
    finish(cu, s);
    if (cu->children.empty()) err("empty compilation unit");
    tree_->root = cu;
    tree_->finalize();
    return std::move(tree_);
  }

 private:
  const std::string& src_;
  std::vector<Token> toks_;
  size_t p_ = 0;
  std::unique_ptr<Tree> tree_;
  // undo log for '>' splitting so speculative parses can rewind cleanly
  std::vector<std::pair<size_t, Token>> undo_;

  // Recursion bound: the library runs in-process (ctypes), so pathological
  // nesting must become ParseError, not a C-stack overflow taking the whole
  // Python worker down. 300 levels also keeps the emitted JSON within
  // Python's default json.loads recursion budget.
  static constexpr int kMaxDepth = 300;
  int depth_ = 0;
  int switch_expr_depth_ = 0;  // yield is a statement ONLY inside switch
                               // EXPRESSION bodies (JLS 14.21) — in switch
                               // STATEMENTS 'yield' stays an identifier
  struct DepthGuard {
    Parser& p;
    explicit DepthGuard(Parser& pp) : p(pp) {
      if (p.depth_ >= kMaxDepth) p.err("nesting too deep");
      ++p.depth_;
    }
    ~DepthGuard() { --p.depth_; }
  };

  // RAII like DepthGuard: speculative-parse catches restore token
  // position but not counters, so the expression-switch depth must unwind
  // on ANY exit (a leak would misparse later 'yield' identifiers)
  struct SwitchExprGuard {
    Parser& p;
    bool on;
    SwitchExprGuard(Parser& pp, bool is_expr) : p(pp), on(is_expr) {
      if (on) ++p.switch_expr_depth_;
    }
    ~SwitchExprGuard() { if (on) --p.switch_expr_depth_; }
  };

  struct State { size_t p, undo; };
  State save() { return {p_, undo_.size()}; }
  void restore(const State& st) {
    while (undo_.size() > st.undo) {
      toks_[undo_.back().first] = undo_.back().second;
      undo_.pop_back();
    }
    p_ = st.p;
  }

  [[noreturn]] void err(const std::string& m) {
    throw ParseError(m + " near '" + cur().text + "' @" +
                     std::to_string(cur().pos));
  }
  const Token& cur() const { return toks_[p_]; }
  const Token& peek(size_t k = 1) const {
    return toks_[std::min(p_ + k, toks_.size() - 1)];
  }
  bool at_end() const { return cur().kind == Tok::End; }
  bool at_op(const char* s) const { return cur().kind == Tok::Op && cur().text == s; }
  bool at_kw(const char* s) const { return cur().kind == Tok::Keyword && cur().text == s; }
  bool at_ident() const { return cur().kind == Tok::Ident; }
  const Token& advance() { return toks_[p_++]; }
  void expect_op(const char* s) { if (!at_op(s)) err(std::string("expected '") + s + "'"); advance(); }
  void expect_kw(const char* s) { if (!at_kw(s)) err(std::string("expected '") + s + "'"); advance(); }
  Token expect_ident() {
    if (!at_ident()) err("expected identifier");
    return advance();
  }

  // Consume one '>' even when the lexer munched '>>', '>>=', '>=', etc.
  void expect_gt() {
    if (at_op(">")) { advance(); return; }
    if (cur().kind == Tok::Op && !cur().text.empty() && cur().text[0] == '>') {
      undo_.emplace_back(p_, cur());
      toks_[p_].text = cur().text.substr(1);
      toks_[p_].pos += 1;
      return;
    }
    err("expected '>'");
  }

  size_t mark() const { return p_; }
  Node* node(const char* typeLabel) { return tree_->make(typeLabel); }
  void finish(Node* n, size_t start_tok) {
    n->pos = toks_[start_tok].pos;
    const Token& last = toks_[p_ > start_tok ? p_ - 1 : start_tok];
    n->length = last.pos + static_cast<int>(last.text.size()) - n->pos;
  }
  Node* leaf(const char* typeLabel, const Token& tk, bool with_label = true) {
    Node* n = node(typeLabel);
    n->pos = tk.pos;
    n->length = static_cast<int>(tk.text.size());
    if (with_label) { n->label = tk.text; n->has_label = true; }
    return n;
  }

  // ------------------------------------------------------------- names ----
  // Dotted name as ONE leaf (SimpleName if undotted, QualifiedName if dotted).
  Node* parse_name_leaf() {
    size_t s = mark();
    std::string text = expect_ident().text;
    while (at_op(".") && peek().kind == Tok::Ident) {
      advance();
      text += "." + advance().text;
    }
    Node* n = node(text.find('.') == std::string::npos ? "SimpleName"
                                                       : "QualifiedName");
    n->label = text; n->has_label = true;
    finish(n, s);
    return n;
  }
  Node* simple_name() { return leaf("SimpleName", expect_ident()); }

  // --------------------------------------------------------- annotations ---
  bool at_annotation() const {
    return at_op("@") && peek().kind == Tok::Ident;
  }
  Node* parse_annotation() {
    DepthGuard dg(*this);
    size_t s = mark();
    expect_op("@");
    Node* name = parse_name_leaf();
    Node* n;
    if (at_op("(")) {
      advance();
      if (at_op(")")) {
        advance();
        n = node("NormalAnnotation");
        n->children.push_back(name);
      } else {
        bool pairs = at_ident() && peek().kind == Tok::Op && peek().text == "=";
        if (pairs) {
          n = node("NormalAnnotation");
          n->children.push_back(name);
          while (true) {
            size_t ps = mark();
            Node* pair = node("MemberValuePair");
            pair->children.push_back(simple_name());
            expect_op("=");
            pair->children.push_back(parse_annotation_value());
            finish(pair, ps);
            n->children.push_back(pair);
            if (at_op(",")) { advance(); continue; }
            break;
          }
        } else {
          n = node("SingleMemberAnnotation");
          n->children.push_back(name);
          n->children.push_back(parse_annotation_value());
        }
        expect_op(")");
      }
    } else {
      n = node("MarkerAnnotation");
      n->children.push_back(name);
    }
    finish(n, s);
    return n;
  }
  Node* parse_annotation_value() {
    if (at_op("{")) {  // array initializer value
      return parse_array_initializer();
    }
    if (at_annotation()) return parse_annotation();
    return parse_expression();
  }

  // modifiers + annotations, interleaved (JDT keeps them in source order)
  void parse_modifiers(std::vector<Node*>& out) {
    while (true) {
      if (at_annotation()) { out.push_back(parse_annotation()); continue; }
      // Java 17 sealed-class modifiers are contextual identifiers: accept
      // `sealed` when what follows keeps reading as a declaration head, and
      // `non-sealed` by fusing its three tokens into one Modifier leaf
      if (cur().kind == Tok::Ident && cur().text == "sealed" &&
          (peek().kind == Tok::Keyword ||
           (peek().kind == Tok::Op && peek().text == "@") ||
           (peek().kind == Tok::Ident &&
            (peek().text == "sealed" || peek().text == "non")))) {
        out.push_back(leaf("Modifier", advance()));
        continue;
      }
      if (cur().kind == Tok::Ident && cur().text == "non" &&
          peek().kind == Tok::Op && peek().text == "-" &&
          peek(2).kind == Tok::Ident && peek(2).text == "sealed") {
        Token fused = cur();
        fused.text = "non-sealed";
        advance(); advance(); advance();
        out.push_back(leaf("Modifier", fused));
        continue;
      }
      if ((cur().kind == Tok::Keyword || cur().kind == Tok::Ident) &&
          is_modifier(cur().text)) {
        // 'default' only a modifier inside interfaces; 'default:' is a switch
        // label — guard on the next token.
        if (cur().text == "default" && peek().kind == Tok::Op &&
            peek().text == ":")
          break;
        if (cur().text == "synchronized" && peek().kind == Tok::Op &&
            peek().text == "(")
          break;  // synchronized-statement, not a modifier
        out.push_back(leaf("Modifier", advance()));
        continue;
      }
      break;
    }
  }

  // --------------------------------------------------------------- types ---
  bool at_type_start() const {
    return at_ident() || (cur().kind == Tok::Keyword && is_primitive(cur().text));
  }

  Node* wrap_simple_type(Node* name_leaf, size_t s) {
    Node* st = node("SimpleType");
    st->children.push_back(name_leaf);
    finish(st, s);
    return st;
  }

  Node* parse_type() {
    DepthGuard dg(*this);
    size_t s = mark();
    Node* base;
    if (cur().kind == Tok::Keyword && is_primitive(cur().text)) {
      base = leaf("PrimitiveType", advance());
    } else {
      base = parse_class_type();
    }
    while (at_op("[") && peek().kind == Tok::Op && peek().text == "]") {
      advance(); advance();
      Node* at = node("ArrayType");
      at->children.push_back(base);
      finish(at, s);
      base = at;
    }
    return base;
  }

  Node* parse_class_type() {
    size_t s = mark();
    if (!at_ident()) err("expected type name");
    // accumulate dotted prefix until a '<' forces a parameterized split
    std::string text = advance().text;
    Node* built = nullptr;  // the type built so far (Simple/Parameterized/Qualified)
    while (true) {
      if (at_op("<") && type_args_ahead()) {
        Node* nm = node(text.find('.') == std::string::npos ? "SimpleName"
                                                            : "QualifiedName");
        nm->label = text; nm->has_label = true;
        finish(nm, s);  // approx span: start..current
        Node* st = built ? qualify(built, nm, s) : wrap_simple_type(nm, s);
        Node* pt = node("ParameterizedType");
        pt->children.push_back(st);
        parse_type_args(pt->children);
        finish(pt, s);
        built = pt;
        text.clear();
        if (at_op(".") && peek().kind == Tok::Ident) {
          advance();
          text = advance().text;
          continue;
        }
        break;
      }
      if (!built && at_op(".") && peek().kind == Tok::Ident) {
        advance();
        text += "." + advance().text;
        continue;
      }
      if (built && !text.empty()) {
        // Outer<T>.Inner (no own type args)
        Node* nm = node("SimpleName");
        nm->label = text; nm->has_label = true;
        finish(nm, s);
        built = qualify(built, nm, s);
        text.clear();
        if (at_op(".") && peek().kind == Tok::Ident) {
          advance();
          text = advance().text;
          continue;
        }
      }
      break;
    }
    if (!built) {
      Node* nm = node(text.find('.') == std::string::npos ? "SimpleName"
                                                          : "QualifiedName");
      nm->label = text; nm->has_label = true;
      finish(nm, s);
      built = wrap_simple_type(nm, s);
    }
    return built;
  }

  Node* qualify(Node* qualifier_type, Node* name, size_t s) {
    Node* qt = node("QualifiedType");
    qt->children.push_back(qualifier_type);
    qt->children.push_back(name);
    finish(qt, s);
    return qt;
  }

  // Speculation: does a well-formed type-argument list start here?
  bool type_args_ahead() {
    State st = save();
    bool ok = try_skip_type_args();
    restore(st);
    return ok;
  }
  bool try_skip_type_args() {
    try {
      parse_type_args_into_scratch();
      return true;
    } catch (const ParseError&) {
      return false;
    }
  }
  void parse_type_args_into_scratch() {
    std::vector<Node*> scratch;
    parse_type_args(scratch);
  }
  void parse_type_args(std::vector<Node*>& out) {
    expect_op("<");
    if (at_op(">")) { advance(); return; }  // diamond
    // Diamond whose '>' was lexed into the enclosing list's closer ('<>>'):
    // split the '>>', consuming one '>' and leaving one for the outer list.
    if (cur().kind == Tok::Op && cur().text == ">>") { expect_gt(); return; }
    while (true) {
      if (at_op("?")) {
        size_t ws = mark();
        advance();
        Node* w = node("WildcardType");
        if (at_kw("extends") || at_kw("super")) {
          advance();
          w->children.push_back(parse_type());
        }
        finish(w, ws);
        out.push_back(w);
      } else {
        out.push_back(parse_type());
      }
      if (at_op(",")) { advance(); continue; }
      break;
    }
    expect_gt();
  }

  // ---------------------------------------------------------- type decls ---
  Node* parse_package() {
    size_t s = mark();
    expect_kw("package");
    Node* n = node("PackageDeclaration");
    n->children.push_back(parse_name_leaf());
    if (at_op(";")) advance();
    finish(n, s);
    return n;
  }

  Node* parse_import() {
    size_t s = mark();
    expect_kw("import");
    if (at_kw("static")) advance();
    Node* n = node("ImportDeclaration");
    n->children.push_back(parse_name_leaf());
    if (at_op(".") && peek().kind == Tok::Op && peek().text == "*") {
      advance(); advance();
    }
    if (at_op(";")) advance();
    finish(n, s);
    return n;
  }

  Node* parse_type_declaration() {
    size_t s = mark();
    std::vector<Node*> mods;
    parse_modifiers(mods);
    if (at_kw("class") || at_kw("interface"))
      return parse_class_or_interface(mods, s);
    if (at_kw("enum")) return parse_enum(mods, s);
    if (at_record()) return parse_record(mods, s);
    if (at_op("@") && peek().kind == Tok::Keyword && peek().text == "interface")
      return parse_annotation_type(mods, s);
    err("expected type declaration");
  }

  // 'record' is a contextual keyword (Java 16): a declaration only when
  // followed by a name and its component list's '(' (or '<' type params)
  bool at_record() const {
    return cur().kind == Tok::Ident && cur().text == "record" &&
           peek().kind == Tok::Ident &&
           peek(2).kind == Tok::Op &&
           (peek(2).text == "(" || peek(2).text == "<");
  }

  Node* parse_record(std::vector<Node*>& mods, size_t s) {
    DepthGuard dg(*this);
    advance();  // 'record'
    Node* n = node("RecordDeclaration");
    n->children = mods;
    n->children.push_back(simple_name());
    if (at_op("<")) parse_type_params(n->children);
    expect_op("(");
    while (!at_op(")")) {
      if (at_end()) err("unterminated record components");
      // components share the full parameter grammar (annotations, varargs,
      // trailing [])
      n->children.push_back(parse_param());
      if (at_op(",")) { advance(); continue; }
      break;
    }
    expect_op(")");
    if (at_kw("implements")) {
      advance();
      n->children.push_back(parse_type());
      while (at_op(",")) { advance(); n->children.push_back(parse_type()); }
    }
    parse_class_body(n->children);
    finish(n, s);
    return n;
  }

  Node* parse_class_or_interface(std::vector<Node*>& mods, size_t s) {
    DepthGuard dg(*this);
    advance();  // class|interface
    Node* n = node("TypeDeclaration");
    n->children = mods;
    n->children.push_back(simple_name());
    if (at_op("<")) parse_type_params(n->children);
    if (at_kw("extends")) {
      advance();
      n->children.push_back(parse_type());
      while (at_op(",")) { advance(); n->children.push_back(parse_type()); }
    }
    if (at_kw("implements")) {
      advance();
      n->children.push_back(parse_type());
      while (at_op(",")) { advance(); n->children.push_back(parse_type()); }
    }
    // Java 17 permits clause (contextual keyword: only '{' may follow the
    // heritage clauses, so a bare identifier here is unambiguous)
    if (cur().kind == Tok::Ident && cur().text == "permits") {
      advance();
      n->children.push_back(parse_type());
      while (at_op(",")) { advance(); n->children.push_back(parse_type()); }
    }
    parse_class_body(n->children);
    finish(n, s);
    return n;
  }

  void parse_type_params(std::vector<Node*>& out) {
    expect_op("<");
    while (true) {
      size_t s = mark();
      while (at_annotation()) parse_annotation();  // drop on type params
      Node* tp = node("TypeParameter");
      tp->children.push_back(simple_name());
      if (at_kw("extends")) {
        advance();
        tp->children.push_back(parse_type());
        while (at_op("&")) { advance(); tp->children.push_back(parse_type()); }
      }
      finish(tp, s);
      out.push_back(tp);
      if (at_op(",")) { advance(); continue; }
      break;
    }
    expect_gt();
  }

  void parse_class_body(std::vector<Node*>& out) {
    expect_op("{");
    while (!at_op("}")) {
      if (at_end()) err("unterminated class body");
      if (at_op(";")) { advance(); continue; }
      out.push_back(parse_member());
    }
    advance();
  }

  Node* parse_member() {
    size_t s = mark();
    std::vector<Node*> mods;
    parse_modifiers(mods);
    if (at_kw("class") || at_kw("interface"))
      return parse_class_or_interface(mods, s);
    if (at_kw("enum")) return parse_enum(mods, s);
    if (at_record()) return parse_record(mods, s);
    if (at_op("@") && peek().kind == Tok::Keyword && peek().text == "interface")
      return parse_annotation_type(mods, s);
    // record compact constructor: Ident '{' occurs for no other member form
    if (at_ident() && peek().kind == Tok::Op && peek().text == "{") {
      Node* n = node("MethodDeclaration");
      n->children = mods;
      n->children.push_back(simple_name());
      n->children.push_back(parse_block());
      finish(n, s);
      return n;
    }
    if (at_op("{")) {  // initializer block (mods may hold 'static')
      Node* n = node("Initializer");
      n->children = mods;
      n->children.push_back(parse_block());
      finish(n, s);
      return n;
    }
    std::vector<Node*> tparams;
    if (at_op("<")) parse_type_params(tparams);
    // constructor: Ident '('
    if (at_ident() && peek().kind == Tok::Op && peek().text == "(") {
      Node* n = node("MethodDeclaration");
      n->children = mods;
      for (Node* tp : tparams) n->children.push_back(tp);
      n->children.push_back(simple_name());
      parse_method_rest(n);
      finish(n, s);
      return n;
    }
    Node* type = parse_type();
    Token name = expect_ident();
    if (at_op("(")) {
      Node* n = node("MethodDeclaration");
      n->children = mods;
      for (Node* tp : tparams) n->children.push_back(tp);
      n->children.push_back(type);
      n->children.push_back(leaf("SimpleName", name));
      parse_method_rest(n);
      // annotation-type member: `type name() default v;`
      finish(n, s);
      return n;
    }
    // field
    Node* n = node("FieldDeclaration");
    n->children = mods;
    n->children.push_back(type);
    parse_fragments(n->children, name);
    expect_op(";");
    finish(n, s);
    return n;
  }

  void parse_method_rest(Node* n) {
    expect_op("(");
    if (!at_op(")")) {
      while (true) {
        n->children.push_back(parse_param());
        if (at_op(",")) { advance(); continue; }
        break;
      }
    }
    expect_op(")");
    while (at_op("[") && peek().kind == Tok::Op && peek().text == "]") {
      advance(); advance();  // legacy `int foo()[]`
    }
    if (at_kw("throws")) {
      advance();
      while (true) {
        size_t ts = mark();
        Node* name = parse_name_leaf();
        n->children.push_back(wrap_simple_type(name, ts));
        if (at_op(",")) { advance(); continue; }
        break;
      }
    }
    if (at_kw("default")) {  // annotation member default
      advance();
      n->children.push_back(parse_annotation_value());
    }
    if (at_op("{")) {
      n->children.push_back(parse_block());
    } else {
      expect_op(";");
    }
  }

  Node* parse_param() {
    size_t s = mark();
    Node* n = node("SingleVariableDeclaration");
    parse_modifiers(n->children);
    n->children.push_back(parse_type());
    if (at_op("...")) advance();  // varargs
    n->children.push_back(simple_name());
    while (at_op("[") && peek().kind == Tok::Op && peek().text == "]") {
      advance(); advance();
    }
    finish(n, s);
    return n;
  }

  void parse_fragments(std::vector<Node*>& out, Token first_name) {
    Token name = first_name;
    while (true) {
      Node* frag = node("VariableDeclarationFragment");
      Node* nm = leaf("SimpleName", name);
      frag->children.push_back(nm);
      frag->pos = nm->pos;
      while (at_op("[") && peek().kind == Tok::Op && peek().text == "]") {
        advance(); advance();
      }
      if (at_op("=")) {
        advance();
        frag->children.push_back(at_op("{") ? parse_array_initializer()
                                            : parse_expression());
      }
      const Token& last = toks_[p_ - 1];
      frag->length = last.pos + static_cast<int>(last.text.size()) - frag->pos;
      out.push_back(frag);
      if (at_op(",")) {
        advance();
        name = expect_ident();
        continue;
      }
      break;
    }
  }

  Node* parse_enum(std::vector<Node*>& mods, size_t s) {
    DepthGuard dg(*this);
    expect_kw("enum");
    Node* n = node("EnumDeclaration");
    n->children = mods;
    n->children.push_back(simple_name());
    if (at_kw("implements")) {
      advance();
      n->children.push_back(parse_type());
      while (at_op(",")) { advance(); n->children.push_back(parse_type()); }
    }
    expect_op("{");
    // constants
    while (!at_op("}") && !at_op(";")) {
      size_t cs = mark();
      Node* c = node("EnumConstantDeclaration");
      while (at_annotation()) c->children.push_back(parse_annotation());
      c->children.push_back(simple_name());
      if (at_op("(")) {
        advance();
        if (!at_op(")")) {
          while (true) {
            c->children.push_back(parse_expression());
            if (at_op(",")) { advance(); continue; }
            break;
          }
        }
        expect_op(")");
      }
      if (at_op("{")) {
        size_t as = mark();
        Node* anon = node("AnonymousClassDeclaration");
        parse_class_body(anon->children);
        finish(anon, as);
        c->children.push_back(anon);
      }
      finish(c, cs);
      n->children.push_back(c);
      if (at_op(",")) { advance(); continue; }
      break;
    }
    if (at_op(";")) {
      advance();
      while (!at_op("}")) {
        if (at_end()) err("unterminated enum body");
        if (at_op(";")) { advance(); continue; }
        n->children.push_back(parse_member());
      }
    }
    expect_op("}");
    finish(n, s);
    return n;
  }

  Node* parse_annotation_type(std::vector<Node*>& mods, size_t s) {
    expect_op("@");
    expect_kw("interface");
    Node* n = node("AnnotationTypeDeclaration");
    n->children = mods;
    n->children.push_back(simple_name());
    expect_op("{");
    while (!at_op("}")) {
      if (at_end()) err("unterminated annotation type body");
      if (at_op(";")) { advance(); continue; }
      size_t ms = mark();
      std::vector<Node*> mmods;
      parse_modifiers(mmods);
      if (at_kw("class") || at_kw("interface")) {
        n->children.push_back(parse_class_or_interface(mmods, ms));
        continue;
      }
      Node* type = parse_type();
      Token name = expect_ident();
      if (at_op("(")) {
        Node* m = node("AnnotationTypeMemberDeclaration");
        m->children = mmods;
        m->children.push_back(type);
        m->children.push_back(leaf("SimpleName", name));
        expect_op("(");
        expect_op(")");
        if (at_kw("default")) {
          advance();
          m->children.push_back(parse_annotation_value());
        }
        expect_op(";");
        finish(m, ms);
        n->children.push_back(m);
      } else {
        Node* f = node("FieldDeclaration");
        f->children = mmods;
        f->children.push_back(type);
        parse_fragments(f->children, name);
        expect_op(";");
        finish(f, ms);
        n->children.push_back(f);
      }
    }
    advance();
    finish(n, s);
    return n;
  }

  // ---------------------------------------------------------- statements ---
  Node* parse_block() {
    size_t s = mark();
    expect_op("{");
    Node* n = node("Block");
    while (!at_op("}")) {
      if (at_end()) err("unterminated block");
      n->children.push_back(parse_statement());
    }
    advance();
    finish(n, s);
    return n;
  }

  Node* parse_statement() {
    DepthGuard dg(*this);
    size_t s = mark();
    if (at_op("{")) return parse_block();
    if (at_op(";")) { advance(); Node* n = node("EmptyStatement"); finish(n, s); return n; }
    if (at_kw("if")) {
      advance();
      Node* n = node("IfStatement");
      expect_op("(");
      n->children.push_back(parse_expression());
      expect_op(")");
      n->children.push_back(parse_statement());
      if (at_kw("else")) {
        advance();
        n->children.push_back(parse_statement());
      }
      finish(n, s);
      return n;
    }
    if (at_kw("while")) {
      advance();
      Node* n = node("WhileStatement");
      expect_op("(");
      n->children.push_back(parse_expression());
      expect_op(")");
      n->children.push_back(parse_statement());
      finish(n, s);
      return n;
    }
    if (at_kw("do")) {
      advance();
      Node* n = node("DoStatement");
      n->children.push_back(parse_statement());
      expect_kw("while");
      expect_op("(");
      n->children.push_back(parse_expression());
      expect_op(")");
      if (at_op(";")) advance();
      finish(n, s);
      return n;
    }
    if (at_kw("for")) return parse_for(s);
    if (at_kw("switch")) return parse_switch(s);
    if (at_kw("try")) return parse_try(s);
    if (at_kw("return")) {
      advance();
      Node* n = node("ReturnStatement");
      if (!at_op(";")) n->children.push_back(parse_expression());
      expect_op(";");
      finish(n, s);
      return n;
    }
    if (at_kw("throw")) {
      advance();
      Node* n = node("ThrowStatement");
      n->children.push_back(parse_expression());
      expect_op(";");
      finish(n, s);
      return n;
    }
    if (at_kw("break") || at_kw("continue")) {
      bool brk = cur().text == "break";
      advance();
      Node* n = node(brk ? "BreakStatement" : "ContinueStatement");
      if (at_ident()) n->children.push_back(simple_name());
      expect_op(";");
      finish(n, s);
      return n;
    }
    if (at_kw("synchronized")) {
      advance();
      Node* n = node("SynchronizedStatement");
      expect_op("(");
      n->children.push_back(parse_expression());
      expect_op(")");
      n->children.push_back(parse_block());
      finish(n, s);
      return n;
    }
    if (at_kw("assert")) {
      advance();
      Node* n = node("AssertStatement");
      n->children.push_back(parse_expression());
      if (at_op(":")) {
        advance();
        n->children.push_back(parse_expression());
      }
      expect_op(";");
      finish(n, s);
      return n;
    }
    if (at_kw("class") || at_kw("interface") || at_kw("enum")) {
      Node* n = node("TypeDeclarationStatement");
      std::vector<Node*> nomods;
      if (at_kw("enum")) n->children.push_back(parse_enum(nomods, s));
      else n->children.push_back(parse_class_or_interface(nomods, s));
      finish(n, s);
      return n;
    }
    // yield statement (contextual keyword, Java 14): inside a switch
    // EXPRESSION body a statement starting with 'yield' is always the
    // yield statement (JLS 14.21 — assigning to a variable named yield
    // there requires qualification); in switch STATEMENTS this branch is
    // dead and 'yield' remains a plain identifier
    if (switch_expr_depth_ > 0 && at_ident() && cur().text == "yield") {
      advance();
      Node* n = node("YieldStatement");
      n->children.push_back(parse_expression());
      expect_op(";");
      finish(n, s);
      return n;
    }
    // labeled statement: Ident ':' stmt
    if (at_ident() && peek().kind == Tok::Op && peek().text == ":" &&
        !(peek(2).kind == Tok::Op && peek(2).text == ":")) {
      Node* n = node("LabeledStatement");
      n->children.push_back(simple_name());
      advance();  // ':'
      n->children.push_back(parse_statement());
      finish(n, s);
      return n;
    }
    // modifier/annotation-led local declaration, or class decl with mods
    if (at_annotation() ||
        ((cur().kind == Tok::Keyword || cur().kind == Tok::Ident) &&
         is_modifier(cur().text) &&
         !(cur().text == "synchronized"))) {
      std::vector<Node*> mods;
      parse_modifiers(mods);
      if (at_kw("class") || at_kw("interface") || at_kw("enum")) {
        Node* n = node("TypeDeclarationStatement");
        if (at_kw("enum")) n->children.push_back(parse_enum(mods, s));
        else n->children.push_back(parse_class_or_interface(mods, s));
        finish(n, s);
        return n;
      }
      Node* n = node("VariableDeclarationStatement");
      n->children = mods;
      n->children.push_back(parse_type());
      parse_fragments(n->children, expect_ident());
      expect_op(";");
      finish(n, s);
      return n;
    }
    // local variable declaration vs expression statement — speculative
    if (at_type_start()) {
      State st = save();
      try {
        Node* type = parse_type();
        if (at_ident()) {
          Token name = advance();
          if (at_op("=") || at_op(";") || at_op(",") ||
              (at_op("[") && peek().kind == Tok::Op && peek().text == "]")) {
            Node* n = node("VariableDeclarationStatement");
            n->children.push_back(type);
            parse_fragments(n->children, name);
            expect_op(";");
            finish(n, s);
            return n;
          }
        }
        restore(st);
      } catch (const ParseError&) {
        restore(st);
      }
    }
    // expression statement
    Node* n = node("ExpressionStatement");
    n->children.push_back(parse_expression());
    expect_op(";");
    finish(n, s);
    return n;
  }

  Node* parse_for(size_t s) {
    expect_kw("for");
    expect_op("(");
    // enhanced for: [mods] Type Ident ':' — speculative
    State st = save();
    try {
      std::vector<Node*> mods;
      parse_modifiers(mods);
      if (at_type_start()) {
        size_t ps = mark();
        Node* type = parse_type();
        if (at_ident()) {
          Token name = advance();
          if (at_op(":")) {
            advance();
            Node* n = node("EnhancedForStatement");
            Node* param = node("SingleVariableDeclaration");
            param->children = mods;
            param->children.push_back(type);
            param->children.push_back(leaf("SimpleName", name));
            finish(param, mods.empty() ? ps : st.p);
            n->children.push_back(param);
            n->children.push_back(parse_expression());
            expect_op(")");
            n->children.push_back(parse_statement());
            finish(n, s);
            return n;
          }
        }
      }
      restore(st);
    } catch (const ParseError&) {
      restore(st);
    }
    Node* n = node("ForStatement");
    if (!at_op(";")) {
      // init: declaration (VariableDeclarationExpression) or expression list
      State st2 = save();
      bool decl = false;
      try {
        size_t ds = mark();
        std::vector<Node*> mods;
        parse_modifiers(mods);
        if (at_type_start()) {
          Node* type = parse_type();
          if (at_ident()) {
            Token name = advance();
            if (at_op("=") || at_op(";") || at_op(",")) {
              Node* vde = node("VariableDeclarationExpression");
              vde->children = mods;
              vde->children.push_back(type);
              parse_fragments(vde->children, name);
              finish(vde, ds);
              n->children.push_back(vde);
              decl = true;
            }
          }
        }
        if (!decl) restore(st2);
      } catch (const ParseError&) {
        restore(st2);
      }
      if (!decl) {
        n->children.push_back(parse_expression());
        while (at_op(",")) { advance(); n->children.push_back(parse_expression()); }
      }
    }
    expect_op(";");
    if (!at_op(";")) n->children.push_back(parse_expression());
    expect_op(";");
    if (!at_op(")")) {
      n->children.push_back(parse_expression());
      while (at_op(",")) { advance(); n->children.push_back(parse_expression()); }
    }
    expect_op(")");
    n->children.push_back(parse_statement());
    finish(n, s);
    return n;
  }

  Node* parse_switch(size_t s) {
    expect_kw("switch");
    Node* n = node("SwitchStatement");
    expect_op("(");
    n->children.push_back(parse_expression());
    expect_op(")");
    parse_switch_block(n, /*is_expr=*/false);
    finish(n, s);
    return n;
  }

  // Shared by SwitchStatement and SwitchExpression: classic `case X:` arms,
  // Java 14 `case A, B -> body` arms (body = expression ';' | block |
  // throw). Yield statements are recognized only under is_expr (JLS 14.21:
  // yield exists only in switch-expression bodies; javac parses any
  // statement there starting with 'yield' as a YieldStatement, while in a
  // switch STATEMENT 'yield' is an ordinary identifier).
  void parse_switch_block(Node* n, bool is_expr) {
    expect_op("{");
    SwitchExprGuard guard(*this, is_expr);
    while (!at_op("}")) {
      if (at_end()) err("unterminated switch");
      if (at_kw("case") || at_kw("default")) {
        size_t cs = mark();
        Node* c = node("SwitchCase");
        if (cur().text == "case") {
          advance();
          c->children.push_back(parse_expression());
          while (at_op(",")) {
            advance();
            c->children.push_back(parse_expression());
          }
        } else {
          advance();
        }
        if (at_op("->")) {
          advance();
          finish(c, cs);
          n->children.push_back(c);
          if (at_op("{")) {
            n->children.push_back(parse_block());
          } else if (at_kw("throw")) {
            n->children.push_back(parse_statement());
          } else {
            size_t es = mark();
            Node* st = node("ExpressionStatement");
            st->children.push_back(parse_expression());
            expect_op(";");
            finish(st, es);
            n->children.push_back(st);
          }
        } else {
          expect_op(":");
          finish(c, cs);
          n->children.push_back(c);
        }
      } else {
        n->children.push_back(parse_statement());
      }
    }
    advance();
  }

  Node* parse_try(size_t s) {
    expect_kw("try");
    Node* n = node("TryStatement");
    if (at_op("(")) {  // try-with-resources
      advance();
      while (!at_op(")")) {
        size_t rs = mark();
        std::vector<Node*> mods;
        parse_modifiers(mods);
        Node* vde = node("VariableDeclarationExpression");
        vde->children = mods;
        vde->children.push_back(parse_type());
        parse_fragments(vde->children, expect_ident());
        finish(vde, rs);
        n->children.push_back(vde);
        if (at_op(";")) { advance(); continue; }
        break;
      }
      expect_op(")");
    }
    n->children.push_back(parse_block());
    while (at_kw("catch")) {
      size_t cs = mark();
      advance();
      Node* cc = node("CatchClause");
      expect_op("(");
      size_t vs = mark();
      Node* param = node("SingleVariableDeclaration");
      parse_modifiers(param->children);
      Node* first = parse_type();
      if (at_op("|")) {
        size_t us = vs;
        Node* ut = node("UnionType");
        ut->children.push_back(first);
        while (at_op("|")) {
          advance();
          ut->children.push_back(parse_type());
        }
        finish(ut, us);
        first = ut;
      }
      param->children.push_back(first);
      param->children.push_back(simple_name());
      finish(param, vs);
      cc->children.push_back(param);
      expect_op(")");
      cc->children.push_back(parse_block());
      finish(cc, cs);
      n->children.push_back(cc);
    }
    if (at_kw("finally")) {
      advance();
      n->children.push_back(parse_block());
    }
    finish(n, s);
    return n;
  }

  // --------------------------------------------------------- expressions ---
  Node* parse_expression() { return parse_assignment(); }

  bool at_assign_op() const {
    if (cur().kind != Tok::Op) return false;
    const std::string& t = cur().text;
    return t == "=" || t == "+=" || t == "-=" || t == "*=" || t == "/=" ||
           t == "%=" || t == "&=" || t == "|=" || t == "^=" || t == "<<=" ||
           t == ">>=" || t == ">>>=";
  }

  Node* parse_assignment() {
    DepthGuard dg(*this);
    size_t s = mark();
    Node* lhs = parse_conditional();
    if (at_assign_op()) {
      std::string op = advance().text;
      Node* n = node("Assignment");
      n->label = op; n->has_label = true;
      n->children.push_back(lhs);
      n->children.push_back(at_op("{") ? parse_array_initializer()
                                       : parse_assignment());
      finish(n, s);
      return n;
    }
    return lhs;
  }

  Node* parse_conditional() {
    DepthGuard dg(*this);
    size_t s = mark();
    Node* c = parse_binary(0);
    if (at_op("?")) {
      advance();
      Node* n = node("ConditionalExpression");
      n->children.push_back(c);
      n->children.push_back(parse_expression());
      expect_op(":");
      n->children.push_back(parse_conditional());
      finish(n, s);
      return n;
    }
    return c;
  }

  // precedence levels, lowest first
  int binop_level(const std::string& t) const {
    if (t == "||") return 1;
    if (t == "&&") return 2;
    if (t == "|") return 3;
    if (t == "^") return 4;
    if (t == "&") return 5;
    if (t == "==" || t == "!=") return 6;
    if (t == "<" || t == ">" || t == "<=" || t == ">=") return 7;  // + instanceof
    if (t == "<<" || t == ">>" || t == ">>>") return 8;
    if (t == "+" || t == "-") return 9;
    if (t == "*" || t == "/" || t == "%") return 10;
    return -1;
  }

  Node* parse_binary(int min_level) {
    size_t s = mark();
    Node* lhs = parse_unary();
    while (true) {
      if (at_kw("instanceof") && min_level <= 7) {
        advance();
        Node* n = node("InstanceofExpression");
        n->children.push_back(lhs);
        n->children.push_back(parse_type());
        // Java 16 pattern variable: `o instanceof String s` — a bare
        // identifier can follow the type in no other instanceof form
        if (at_ident()) n->children.push_back(simple_name());
        finish(n, s);
        lhs = n;
        continue;
      }
      if (cur().kind != Tok::Op) break;
      int lvl = binop_level(cur().text);
      if (lvl < 0 || lvl < min_level) break;
      // '<' ambiguity with generics is resolved upstream (types are only
      // parsed speculatively); here '<' is always an operator.
      std::string op = advance().text;
      Node* rhs = parse_binary(lvl + 1);
      // JDT flattens same-operator chains into one InfixExpression with
      // extended operands.
      if (lhs->typeLabel == "InfixExpression" && lhs->has_label &&
          lhs->label == op) {
        lhs->children.push_back(rhs);
        const Token& last = toks_[p_ - 1];
        lhs->length = last.pos + static_cast<int>(last.text.size()) - lhs->pos;
      } else {
        Node* n = node("InfixExpression");
        n->label = op; n->has_label = true;
        n->children.push_back(lhs);
        n->children.push_back(rhs);
        finish(n, s);
        lhs = n;
      }
    }
    return lhs;
  }

  Node* parse_unary() {
    DepthGuard dg(*this);
    size_t s = mark();
    if (cur().kind == Tok::Op &&
        (cur().text == "+" || cur().text == "-" || cur().text == "!" ||
         cur().text == "~" || cur().text == "++" || cur().text == "--")) {
      std::string op = advance().text;
      Node* n = node("PrefixExpression");
      n->label = op; n->has_label = true;
      n->children.push_back(parse_unary());
      finish(n, s);
      return n;
    }
    // cast: '(' Type ')' operand
    if (at_op("(")) {
      State st = save();
      try {
        advance();
        Node* type = parse_type();
        if (at_op(")")) {
          advance();
          bool operand_next =
              at_ident() || cur().kind == Tok::Number ||
              cur().kind == Tok::String || cur().kind == Tok::Char ||
              at_op("(") || at_op("!") || at_op("~") ||
              at_kw("this") || at_kw("super") || at_kw("new") ||
              at_kw("true") || at_kw("false") || at_kw("null") ||
              (type->typeLabel == "PrimitiveType" &&
               (at_op("+") || at_op("-")));
          if (operand_next) {
            Node* n = node("CastExpression");
            n->children.push_back(type);
            n->children.push_back(parse_unary());
            finish(n, s);
            return n;
          }
        }
        restore(st);
      } catch (const ParseError&) {
        restore(st);
      }
    }
    return parse_postfix();
  }

  Node* parse_postfix() {
    size_t s = mark();
    Node* e = parse_primary();
    // Postfix chains (a.b().c()[i]...) deepen the tree ITERATIVELY, so the
    // recursive DepthGuard never sees them — bound the wrapping links too, or
    // a pathological chain re-creates the stack-overflow the guard exists to
    // prevent (recursive finalize/serialize/destruct all walk this spine).
    // Only node-WRAPPING branches call bump(): the QualifiedName merge folds
    // arbitrarily many '.name's into one flat leaf and must stay unbounded.
    int links = 0;
    auto bump = [&] {
      if (depth_ + ++links >= kMaxDepth) err("postfix chain too deep");
    };
    while (true) {
      if (at_op(".")) {
        // method invocation / field access / qualified this / inner new /
        // .class handled at primary for type names
        if (peek().kind == Tok::Ident) {
          bool call = peek(2).kind == Tok::Op && peek(2).text == "(";
          if (call) {
            bump();
            advance();  // '.'
            Node* n = node("MethodInvocation");
            n->children.push_back(e);
            n->children.push_back(simple_name());
            parse_args(n->children);
            finish(n, s);
            e = n;
            continue;
          }
          // plain field access; extend Name leaves into QualifiedName
          advance();  // '.'
          Token name = advance();
          if ((e->typeLabel == "SimpleName" || e->typeLabel == "QualifiedName") &&
              e->children.empty()) {
            e->typeLabel = "QualifiedName";
            e->label += "." + name.text;
            e->length = name.pos + static_cast<int>(name.text.size()) - e->pos;
          } else {
            bump();
            Node* n = node("FieldAccess");
            n->children.push_back(e);
            n->children.push_back(leaf("SimpleName", name));
            finish(n, s);
            e = n;
          }
          continue;
        }
        if (peek().kind == Tok::Op && peek().text == "<") {
          // expr.<T>m(...)
          State st = save();
          try {
            bump();
            advance();  // '.'
            std::vector<Node*> targs;
            parse_type_args(targs);
            Node* n = node("MethodInvocation");
            n->children.push_back(e);
            for (Node* a : targs) n->children.push_back(a);
            n->children.push_back(simple_name());
            parse_args(n->children);
            finish(n, s);
            e = n;
            continue;
          } catch (const ParseError&) {
            restore(st);
          }
        }
        if (peek().kind == Tok::Keyword && peek().text == "this") {
          bump();
          advance(); advance();
          Node* n = node("ThisExpression");  // qualified this; no label
          n->children.push_back(e);
          finish(n, s);
          e = n;
          continue;
        }
        if (peek().kind == Tok::Keyword && peek().text == "new") {
          bump();
          advance();
          Node* n = parse_new(s, e);
          e = n;
          continue;
        }
        if (peek().kind == Tok::Keyword && peek().text == "class") {
          // Name.class
          bump();
          advance(); advance();
          Node* tl = node("TypeLiteral");
          if ((e->typeLabel == "SimpleName" || e->typeLabel == "QualifiedName") &&
              e->children.empty()) {
            Node* st = node("SimpleType");
            st->children.push_back(e);
            st->pos = e->pos; st->length = e->length;
            tl->children.push_back(st);
          } else {
            tl->children.push_back(e);
          }
          finish(tl, s);
          e = tl;
          continue;
        }
        if (peek().kind == Tok::Keyword && peek().text == "super") {
          bump();
          // Outer.super.m(...) / Outer.super.x — keep the qualifier as the
          // first child (JDT shape) so its source token stays in the tree.
          advance(); advance();
          expect_op(".");
          Node* name = simple_name();
          Node* n;
          if (at_op("(")) {
            n = node("SuperMethodInvocation");
            n->children.push_back(e);
            n->children.push_back(name);
            parse_args(n->children);
          } else {
            n = node("SuperFieldAccess");
            n->children.push_back(e);
            n->children.push_back(name);
          }
          finish(n, s);
          e = n;
          continue;
        }
        err("unsupported '.' suffix");
      }
      if (at_op("[")) {
        bump();
        advance();
        Node* n = node("ArrayAccess");
        n->children.push_back(e);
        n->children.push_back(parse_expression());
        expect_op("]");
        finish(n, s);
        e = n;
        continue;
      }
      if (at_op("++") || at_op("--")) {
        bump();
        std::string op = advance().text;
        Node* n = node("PostfixExpression");
        n->label = op; n->has_label = true;
        n->children.push_back(e);
        finish(n, s);
        e = n;
        continue;
      }
      if (at_op("::")) {
        bump();
        advance();
        Node* n = node("ExpressionMethodReference");
        n->children.push_back(e);
        if (at_kw("new")) {
          advance();
          Node* nm = node("SimpleName");
          nm->label = "new"; nm->has_label = true;
          nm->pos = toks_[p_ - 1].pos; nm->length = 3;
          n->children.push_back(nm);
        } else {
          n->children.push_back(simple_name());
        }
        finish(n, s);
        e = n;
        continue;
      }
      break;
    }
    return e;
  }

  void parse_args(std::vector<Node*>& out) {
    expect_op("(");
    if (!at_op(")")) {
      while (true) {
        out.push_back(parse_expression());
        if (at_op(",")) { advance(); continue; }
        break;
      }
    }
    expect_op(")");
  }

  Node* parse_array_initializer() {
    DepthGuard dg(*this);
    size_t s = mark();
    expect_op("{");
    Node* n = node("ArrayInitializer");
    while (!at_op("}")) {
      n->children.push_back(at_op("{") ? parse_array_initializer()
                                       : parse_expression());
      if (at_op(",")) { advance(); continue; }
      break;
    }
    expect_op("}");
    finish(n, s);
    return n;
  }

  Node* parse_new(size_t s, Node* outer) {
    expect_kw("new");
    // element type WITHOUT trailing '[]' dims — those belong to the
    // array-creation syntax here (`new int[] {...}`, `new Foo[n]`), so using
    // parse_type() would swallow them and break the '[' dispatch below
    Node* type;
    if (cur().kind == Tok::Keyword && is_primitive(cur().text)) {
      type = leaf("PrimitiveType", advance());
    } else {
      type = parse_class_type();
    }
    if (at_op("[")) {
      // array creation; rebuild element/dims
      Node* n = node("ArrayCreation");
      Node* at = node("ArrayType");
      at->children.push_back(type);
      at->pos = type->pos;
      int ndims = 0;
      std::vector<Node*> dims;
      while (at_op("[")) {
        advance();
        if (!at_op("]")) dims.push_back(parse_expression());
        expect_op("]");
        ++ndims;
      }
      const Token& last = toks_[p_ - 1];
      at->length = last.pos + static_cast<int>(last.text.size()) - at->pos;
      n->children.push_back(at);
      for (Node* d : dims) n->children.push_back(d);
      if (at_op("{")) n->children.push_back(parse_array_initializer());
      finish(n, s);
      return n;
    }
    Node* n = node("ClassInstanceCreation");
    if (outer) n->children.push_back(outer);
    n->children.push_back(type);
    parse_args(n->children);
    if (at_op("{")) {
      size_t as = mark();
      Node* anon = node("AnonymousClassDeclaration");
      parse_class_body(anon->children);
      finish(anon, as);
      n->children.push_back(anon);
    }
    finish(n, s);
    return n;
  }

  // Lambda: Ident '->' | '(' params ')' '->'
  bool lambda_ahead() {
    if (at_ident() && peek().kind == Tok::Op && peek().text == "->") return true;
    if (!at_op("(")) return false;
    // scan to matching ')'
    int depth = 0;
    size_t i = p_;
    while (i < toks_.size() && toks_[i].kind != Tok::End) {
      const std::string& t = toks_[i].text;
      if (toks_[i].kind == Tok::Op) {
        if (t == "(") ++depth;
        else if (t == ")") {
          --depth;
          if (depth == 0) {
            return i + 1 < toks_.size() && toks_[i + 1].kind == Tok::Op &&
                   toks_[i + 1].text == "->";
          }
        }
      }
      ++i;
    }
    return false;
  }

  Node* parse_lambda() {
    size_t s = mark();
    Node* n = node("LambdaExpression");
    if (at_ident()) {
      size_t fs = mark();
      Node* frag = node("VariableDeclarationFragment");
      frag->children.push_back(simple_name());
      finish(frag, fs);
      n->children.push_back(frag);
    } else {
      expect_op("(");
      while (!at_op(")")) {
        State st = save();
        bool typed = false;
        try {
          size_t ps = mark();
          std::vector<Node*> mods;
          parse_modifiers(mods);
          if (at_type_start()) {
            Node* type = parse_type();
            if (at_ident()) {
              Node* param = node("SingleVariableDeclaration");
              param->children = mods;
              param->children.push_back(type);
              param->children.push_back(simple_name());
              finish(param, ps);
              n->children.push_back(param);
              typed = true;
            }
          }
          if (!typed) restore(st);
        } catch (const ParseError&) {
          restore(st);
        }
        if (!typed) {
          size_t fs = mark();
          Node* frag = node("VariableDeclarationFragment");
          frag->children.push_back(simple_name());
          finish(frag, fs);
          n->children.push_back(frag);
        }
        if (at_op(",")) { advance(); continue; }
        break;
      }
      expect_op(")");
    }
    expect_op("->");
    n->children.push_back(at_op("{") ? parse_block() : parse_expression());
    finish(n, s);
    return n;
  }

  Node* parse_primary() {
    size_t s = mark();
    if (lambda_ahead()) return parse_lambda();
    if (at_kw("switch")) {  // Java 14 switch expression
      advance();
      Node* n = node("SwitchExpression");
      expect_op("(");
      n->children.push_back(parse_expression());
      expect_op(")");
      parse_switch_block(n, /*is_expr=*/true);
      finish(n, s);
      return n;
    }
    if (cur().kind == Tok::Number) return leaf("NumberLiteral", advance());
    if (cur().kind == Tok::String) return leaf("StringLiteral", advance());
    if (cur().kind == Tok::Char) return leaf("CharacterLiteral", advance());
    if (at_kw("true") || at_kw("false")) return leaf("BooleanLiteral", advance());
    if (at_kw("null")) return leaf("NullLiteral", advance(), /*with_label=*/false);
    if (at_kw("this")) {
      Token tk = advance();
      if (at_op("(")) {  // this(...) constructor invocation (expression pos)
        Node* n = node("ConstructorInvocation");
        parse_args(n->children);
        finish(n, s);
        return n;
      }
      return leaf("ThisExpression", tk, /*with_label=*/false);
    }
    if (at_kw("super")) {
      Token tk = advance();
      if (at_op("(")) {
        Node* n = node("SuperConstructorInvocation");
        parse_args(n->children);
        finish(n, s);
        return n;
      }
      expect_op(".");
      Token name = expect_ident();
      if (at_op("(")) {
        Node* n = node("SuperMethodInvocation");
        n->children.push_back(leaf("SimpleName", name));
        parse_args(n->children);
        finish(n, s);
        return n;
      }
      Node* n = node("SuperFieldAccess");
      n->children.push_back(leaf("SimpleName", name));
      finish(n, s);
      return n;
    }
    if (at_kw("new")) return parse_new(s, nullptr);
    if (at_op("(")) {
      advance();
      Node* inner = parse_expression();
      expect_op(")");
      Node* n = node("ParenthesizedExpression");
      n->children.push_back(inner);
      finish(n, s);
      return n;
    }
    if (cur().kind == Tok::Keyword && is_primitive(cur().text)) {
      // int.class / int[].class
      Node* type = parse_type();
      expect_op(".");
      expect_kw("class");
      Node* n = node("TypeLiteral");
      n->children.push_back(type);
      finish(n, s);
      return n;
    }
    if (at_ident()) {
      Token name = advance();
      if (at_op("(")) {
        Node* n = node("MethodInvocation");
        n->children.push_back(leaf("SimpleName", name));
        parse_args(n->children);
        finish(n, s);
        return n;
      }
      return leaf("SimpleName", name);
    }
    err("expected expression");
  }
};

}  // namespace

void Tree::finalize() {
  preorder.clear();
  std::function<void(Node*, Node*)> walk = [&](Node* n, Node* parent) {
    n->parent = parent;
    n->id = static_cast<int>(preorder.size());
    preorder.push_back(n);
    n->height = 0;
    n->size = 1;
    uint64_t h = 1469598103934665603ull;
    auto mix = [&h](const std::string& s) {
      for (char c : s) { h ^= static_cast<unsigned char>(c); h *= 1099511628211ull; }
      h ^= 0xff; h *= 1099511628211ull;
    };
    mix(n->typeLabel);
    if (n->has_label) mix(n->label);
    for (Node* c : n->children) {
      walk(c, n);
      n->height = std::max(n->height, c->height + 1);
      n->size += c->size;
      h ^= c->hash + 0x9e3779b97f4a7c15ull + (h << 6) + (h >> 2);
    }
    n->hash = h;
  };
  if (root) walk(root, nullptr);
}

std::unique_ptr<Tree> parse(const std::string& src) {
  Parser p(src);
  return p.run();
}

}  // namespace astdiff
