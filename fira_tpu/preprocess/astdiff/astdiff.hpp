// astdiff: native Java AST parse + GumTree-style tree diff.
//
// TPU-native replacement for the reference's vendored Java GumTree 2.1.2
// distribution (/root/reference/gumtree/, consumed through two CLI contracts
// in /root/reference/Preprocess/get_ast_root_action.py:69-101 `parse` and
// :123-171 `diff`). Implemented from scratch in C++ so the preprocessing
// pipeline needs no JVM and no subprocess-per-chunk: the library is loaded
// once per worker via ctypes and called in-process.
//
// Contracts honoured (the ONLY interface the pipeline depends on):
//   parse:  Java source -> JSON {"root": {id,type,typeLabel,pos,length,
//           children[,label]}}  (leaf label == exact source token text;
//           NullLiteral / ThisExpression carry NO label)
//   diff:   old source + new source -> text lines
//           "Match T[: name](id) to T[: name](id)"
//           "Update T[: name](id) to newname"
//           "Move T[: name](id) into T[: name](id) at k"
//           "Insert T[: name](id) into T[: name](id) at k"
//           "Delete T[: name](id)"
//           where every Move/Update old node also appears in a Match line and
//           every Insert/Move target parent really owns the named child —
//           the invariants the reference bridge asserts.
#pragma once

#include <cstdint>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace astdiff {

// ---------------------------------------------------------------- tokens ---
enum class Tok : uint8_t {
  Ident,
  Keyword,
  Number,
  String,
  Char,
  Op,
  End,
};

struct Token {
  Tok kind;
  std::string text;
  int pos;  // char offset in source
};

struct LexError : std::runtime_error {
  explicit LexError(const std::string& m) : std::runtime_error(m) {}
};

// Tokenize Java source. Comments/whitespace dropped. Throws LexError.
std::vector<Token> lex(const std::string& src);

// ------------------------------------------------------------------ trees ---
struct Node {
  int id = -1;  // preorder index, assigned after parse
  std::string typeLabel;
  std::string label;      // leaf: exact source token text; infix/assign ops
  bool has_label = false; // NullLiteral/ThisExpression: false by contract
  int pos = 0;
  int length = 0;
  std::vector<Node*> children;
  Node* parent = nullptr;

  // matcher scratch
  int height = 0;
  int size = 1;
  uint64_t hash = 0;
};

// Owns every node; Node* stay valid for the Tree's lifetime.
struct Tree {
  std::vector<std::unique_ptr<Node>> arena;
  Node* root = nullptr;
  std::vector<Node*> preorder;  // preorder[i]->id == i

  Node* make(const std::string& typeLabel) {
    arena.push_back(std::make_unique<Node>());
    arena.back()->typeLabel = typeLabel;
    return arena.back().get();
  }
  void finalize();  // assign ids/parents/heights/hashes, fill preorder
};

struct ParseError : std::runtime_error {
  explicit ParseError(const std::string& m) : std::runtime_error(m) {}
};

// Parse a Java compilation unit (the wrapped fragments the FIRA pipeline
// feeds: always a parseable unit starting with package/import/annotation/
// modifier/class). Throws ParseError / LexError on anything it can't handle;
// callers degrade the chunk to code-tokens-only, exactly like the reference
// does when GumTree fails (process_data_ast_parallel.py:204-217).
std::unique_ptr<Tree> parse(const std::string& src);

// JSON per the `parse` contract.
std::string to_json(const Tree& t);

// ------------------------------------------------------------------- diff ---
struct Mapping {
  // old preorder id -> new preorder id (-1 = unmatched), and inverse.
  std::vector<int> o2n, n2o;
};

Mapping match_trees(const Tree& told, const Tree& tnew);

// Action script text per the `diff` contract (includes all Match lines).
std::string diff_actions(const Tree& told, const Tree& tnew);

}  // namespace astdiff
