// GumTree-style tree matching + Chawathe-style edit actions.
//
// Reimplements (from the algorithm, not the code) what the reference gets
// from `gumtree diff a.java b.java` (get_ast_root_action.py:123-171):
//   phase 1  top-down: greedily map isomorphic subtrees, tallest first
//            (subtree hash equality), unique pairs first, ambiguous pairs
//            resolved by parent-mapping agreement then source position;
//   phase 2  bottom-up: an unmatched old container is mapped to the
//            same-type new container sharing the most mapped descendants
//            (dice > 0.5, always for the roots), followed by a last-chance
//            recovery pass pairing leftover same-type/label descendants;
//   actions  Update (label changed), Move (parent mapping disagrees, or
//            child order changed per LCS alignment), Insert / Delete
//            (unmapped), each printed in the exact text the reference
//            bridge parses and re-asserts against both trees.
#include "astdiff.hpp"

#include <algorithm>
#include <cstdlib>
#include <map>
#include <sstream>
#include <unordered_map>
#include <unordered_set>

namespace astdiff {

namespace {

constexpr int kMinHeight = 2;      // GumTree default: subtrees shorter than
                                   // this are left to the bottom-up phase
constexpr double kDiceThreshold = 0.5;

void collect_descendants(const Node* n, std::vector<const Node*>& out) {
  for (const Node* c : n->children) {
    out.push_back(c);
    collect_descendants(c, out);
  }
}

// Map an isomorphic pair subtree-wide (equal hashes => equal shape).
void map_isomorphic(const Node* o, const Node* n, Mapping& m) {
  if (m.o2n[o->id] != -1 || m.n2o[n->id] != -1) return;
  m.o2n[o->id] = n->id;
  m.n2o[n->id] = o->id;
  for (size_t i = 0; i < o->children.size() && i < n->children.size(); ++i)
    map_isomorphic(o->children[i], n->children[i], m);
}

struct HeightList {
  // max-height priority structure over open nodes
  std::map<int, std::vector<Node*>, std::greater<int>> by_height;
  void push(Node* n) { by_height[n->height].push_back(n); }
  int peek() const { return by_height.empty() ? -1 : by_height.begin()->first; }
  std::vector<Node*> pop() {
    auto v = std::move(by_height.begin()->second);
    by_height.erase(by_height.begin());
    return v;
  }
  void open(Node* n) {
    for (Node* c : n->children) push(c);
  }
};

// `od` = o's descendants, precomputed by the caller (shared across the
// candidate loop).
double dice(const std::vector<const Node*>& od, const Node* n,
            const Mapping& m) {
  const size_t n_desc = static_cast<size_t>(n->size) - 1;
  if (od.empty() && n_desc == 0) return 0.0;
  int common = 0;
  for (const Node* d : od) {
    int t = m.o2n[d->id];
    if (t == -1) continue;
    // target inside n's subtree?
    // (ids are preorder: inside iff n.id < t <= n.id + n.size - 1)
    if (t > n->id && t < n->id + n->size) ++common;
  }
  return 2.0 * common / (static_cast<double>(od.size()) + n_desc);
}

std::string node_key(const Node* x) {
  return x->typeLabel + "\x01" + (x->has_label ? x->label : std::string());
}

// Position-respecting recovery: LCS-align the children of a matched pair on
// (typeLabel, label) keys, map aligned unmatched pairs, recurse into them.
// Approximates GumTree's optimal last-chance mapping for containers.
void align_children(const Node* o, const Node* n, Mapping& m) {
  const auto& a = o->children;
  const auto& b = n->children;
  if (a.empty() || b.empty()) return;
  std::vector<std::string> ka(a.size()), kb(b.size());
  for (size_t i = 0; i < a.size(); ++i) ka[i] = node_key(a[i]);
  for (size_t j = 0; j < b.size(); ++j) kb[j] = node_key(b[j]);
  std::vector<std::vector<int>> dp(a.size() + 1,
                                   std::vector<int>(b.size() + 1, 0));
  for (size_t i = a.size(); i-- > 0;)
    for (size_t j = b.size(); j-- > 0;)
      dp[i][j] = (ka[i] == kb[j]) ? dp[i + 1][j + 1] + 1
                                  : std::max(dp[i + 1][j], dp[i][j + 1]);
  for (size_t i = 0, j = 0; i < a.size() && j < b.size();) {
    if (ka[i] == kb[j]) {
      if (m.o2n[a[i]->id] == -1 && m.n2o[b[j]->id] == -1) {
        m.o2n[a[i]->id] = b[j]->id;
        m.n2o[b[j]->id] = a[i]->id;
      }
      if (m.o2n[a[i]->id] == b[j]->id) align_children(a[i], b[j], m);
      ++i; ++j;
    } else if (dp[i + 1][j] >= dp[i][j + 1]) {
      ++i;
    } else {
      ++j;
    }
  }
}

void last_chance(const Node* o, const Node* n, Mapping& m) {
  align_children(o, n, m);
  std::vector<const Node*> od, nd;
  collect_descendants(o, od);
  collect_descendants(n, nd);
  // leftover pass: unique (type,label) pairs, then unique same-type pairs —
  // catches moved nodes the positional alignment couldn't reach
  for (int pass = 0; pass < 2; ++pass) {
    std::unordered_map<std::string, std::vector<const Node*>> og, ng;
    for (const Node* d : od)
      if (m.o2n[d->id] == -1)
        og[pass == 0 ? node_key(d) : d->typeLabel].push_back(d);
    for (const Node* d : nd)
      if (m.n2o[d->id] == -1)
        ng[pass == 0 ? node_key(d) : d->typeLabel].push_back(d);
    for (auto& [k, olds] : og) {
      auto it = ng.find(k);
      if (it == ng.end()) continue;
      auto& news = it->second;
      if (olds.size() == 1 && news.size() == 1) {
        m.o2n[olds[0]->id] = news[0]->id;
        m.n2o[news[0]->id] = olds[0]->id;
        align_children(olds[0], news[0], m);
      }
    }
  }
}

}  // namespace

Mapping match_trees(const Tree& told, const Tree& tnew) {
  Mapping m;
  m.o2n.assign(told.preorder.size(), -1);
  m.n2o.assign(tnew.preorder.size(), -1);

  // ---- phase 1: top-down greedy isomorphic subtree matching ----
  HeightList l1, l2;
  l1.push(told.root);
  l2.push(tnew.root);
  while (std::min(l1.peek(), l2.peek()) >= kMinHeight) {
    if (l1.peek() != l2.peek()) {
      if (l1.peek() > l2.peek())
        for (Node* t : l1.pop()) l1.open(t);
      else
        for (Node* t : l2.pop()) l2.open(t);
      continue;
    }
    std::vector<Node*> olds = l1.pop(), news = l2.pop();
    std::unordered_map<uint64_t, std::vector<Node*>> oh, nh;
    for (Node* t : olds) oh[t->hash].push_back(t);
    for (Node* t : news) nh[t->hash].push_back(t);
    // unique-unique first, then ambiguous resolved by parent mapping / pos
    for (auto& [h, ov] : oh) {
      auto it = nh.find(h);
      if (it == nh.end()) continue;
      auto& nv = it->second;
      if (ov.size() == 1 && nv.size() == 1) {
        map_isomorphic(ov[0], nv[0], m);
      } else {
        struct Cand { Node* o; Node* n; int parent_ok; int posdiff; };
        std::vector<Cand> cands;
        for (Node* o : ov)
          for (Node* n : nv) {
            int pok = (o->parent && n->parent &&
                       m.o2n[o->parent->id] == n->parent->id)
                          ? 1 : 0;
            cands.push_back({o, n, pok, std::abs(o->pos - n->pos)});
          }
        std::stable_sort(cands.begin(), cands.end(),
                         [](const Cand& a, const Cand& b) {
                           if (a.parent_ok != b.parent_ok)
                             return a.parent_ok > b.parent_ok;
                           return a.posdiff < b.posdiff;
                         });
        for (auto& c : cands)
          if (m.o2n[c.o->id] == -1 && m.n2o[c.n->id] == -1)
            map_isomorphic(c.o, c.n, m);
      }
    }
    for (Node* t : olds)
      if (m.o2n[t->id] == -1) l1.open(t);
    for (Node* t : news)
      if (m.n2o[t->id] == -1) l2.open(t);
  }

  // ---- phase 2: bottom-up container matching ----
  // postorder = reverse preorder works for "children before parents" here
  for (auto it = told.preorder.rbegin(); it != told.preorder.rend(); ++it) {
    Node* o = *it;
    if (m.o2n[o->id] != -1 || o->children.empty()) continue;
    bool is_root = (o->parent == nullptr);
    // candidates: ancestors of mappings of o's matched descendants with the
    // same typeLabel
    std::vector<const Node*> od;
    collect_descendants(o, od);
    std::unordered_set<int> candidates;
    for (const Node* d : od) {
      int t = m.o2n[d->id];
      if (t == -1) continue;
      const Node* a = tnew.preorder[t]->parent;
      while (a) {
        if (a->typeLabel == o->typeLabel && m.n2o[a->id] == -1)
          candidates.insert(a->id);
        a = a->parent;
      }
    }
    const Node* best = nullptr;
    double best_dice = -1.0;
    for (int nid : candidates) {
      const Node* c = tnew.preorder[nid];
      double d = dice(od, c, m);
      if (d > best_dice) { best_dice = d; best = c; }
    }
    if (best && (best_dice > kDiceThreshold || is_root)) {
      m.o2n[o->id] = best->id;
      m.n2o[best->id] = o->id;
      last_chance(o, best, m);
    }
  }
  // roots always correspond (both CompilationUnit)
  if (m.o2n[told.root->id] == -1 && m.n2o[tnew.root->id] == -1 &&
      told.root->typeLabel == tnew.root->typeLabel) {
    m.o2n[told.root->id] = tnew.root->id;
    m.n2o[tnew.root->id] = told.root->id;
    last_chance(told.root, tnew.root, m);
  }
  return m;
}

// ------------------------------------------------------------- printing ---
namespace {

std::string fmt_node(const Node* n) {
  std::ostringstream os;
  os << n->typeLabel;
  if (n->has_label) os << ": " << n->label;
  os << "(" << n->id << ")";
  return os.str();
}

int child_index(const Node* parent, const Node* child) {
  for (size_t i = 0; i < parent->children.size(); ++i)
    if (parent->children[i] == child) return static_cast<int>(i);
  return 0;
}

}  // namespace

std::string diff_actions(const Tree& told, const Tree& tnew) {
  Mapping m = match_trees(told, tnew);
  std::ostringstream out;

  // Match lines: every mapped pair, old-preorder order.
  for (const Node* o : told.preorder) {
    int t = m.o2n[o->id];
    if (t == -1) continue;
    out << "Match " << fmt_node(o) << " to " << fmt_node(tnew.preorder[t])
        << "\n";
  }

  // Updates: label changed on a mapped pair.
  for (const Node* o : told.preorder) {
    int t = m.o2n[o->id];
    if (t == -1) continue;
    const Node* n = tnew.preorder[t];
    const std::string ol = o->has_label ? o->label : std::string();
    const std::string nl = n->has_label ? n->label : std::string();
    if (ol != nl) out << "Update " << fmt_node(o) << " to " << nl << "\n";
  }

  // Moves, part 1: parent mapping disagrees.
  std::vector<bool> moved(told.preorder.size(), false);
  for (const Node* o : told.preorder) {
    int t = m.o2n[o->id];
    if (t == -1 || !o->parent) continue;
    const Node* n = tnew.preorder[t];
    if (!n->parent) continue;
    if (m.o2n[o->parent->id] != n->parent->id) {
      moved[o->id] = true;
      out << "Move " << fmt_node(o) << " into " << fmt_node(n->parent)
          << " at " << child_index(n->parent, n) << "\n";
    }
  }
  // Moves, part 2: order changed among siblings mapped to the same parent —
  // LCS alignment; mapped child pairs outside the LCS are moves.
  for (const Node* po : told.preorder) {
    int pt = m.o2n[po->id];
    if (pt == -1) continue;
    const Node* pn = tnew.preorder[pt];
    std::vector<const Node*> s1, s2;
    for (const Node* c : po->children) {
      int t = m.o2n[c->id];
      if (t != -1 && tnew.preorder[t]->parent == pn && !moved[c->id])
        s1.push_back(c);
    }
    for (const Node* d : pn->children) {
      int t = m.n2o[d->id];
      if (t != -1 && told.preorder[t]->parent == po) s2.push_back(d);
    }
    if (s1.size() <= 1) continue;
    // LCS over (s1, s2) with equality "mapped to each other"
    size_t a = s1.size(), b = s2.size();
    std::vector<std::vector<int>> dp(a + 1, std::vector<int>(b + 1, 0));
    for (size_t i = a; i-- > 0;)
      for (size_t j = b; j-- > 0;)
        dp[i][j] = (m.o2n[s1[i]->id] == s2[j]->id)
                       ? dp[i + 1][j + 1] + 1
                       : std::max(dp[i + 1][j], dp[i][j + 1]);
    std::vector<bool> in_lcs(a, false);
    for (size_t i = 0, j = 0; i < a && j < b;) {
      if (m.o2n[s1[i]->id] == s2[j]->id) { in_lcs[i] = true; ++i; ++j; }
      else if (dp[i + 1][j] >= dp[i][j + 1]) ++i;
      else ++j;
    }
    for (size_t i = 0; i < a; ++i) {
      if (in_lcs[i] || moved[s1[i]->id]) continue;
      const Node* n = tnew.preorder[m.o2n[s1[i]->id]];
      moved[s1[i]->id] = true;
      out << "Move " << fmt_node(s1[i]) << " into " << fmt_node(pn) << " at "
          << child_index(pn, n) << "\n";
    }
  }

  // Inserts: unmapped new nodes (preorder).
  for (const Node* n : tnew.preorder) {
    if (m.n2o[n->id] != -1 || !n->parent) continue;
    out << "Insert " << fmt_node(n) << " into " << fmt_node(n->parent)
        << " at " << child_index(n->parent, n) << "\n";
  }
  // Deletes: unmapped old nodes (preorder).
  for (const Node* o : told.preorder) {
    if (m.o2n[o->id] != -1 || !o->parent) continue;
    out << "Delete " << fmt_node(o) << "\n";
  }
  return out.str();
}

// ---------------------------------------------------------------- JSON ----
namespace {

void json_escape(const std::string& s, std::ostringstream& os) {
  for (char c : s) {
    switch (c) {
      case '"': os << "\\\""; break;
      case '\\': os << "\\\\"; break;
      case '\n': os << "\\n"; break;
      case '\r': os << "\\r"; break;
      case '\t': os << "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          os << buf;
        } else {
          os << c;
        }
    }
  }
}

int type_code(const std::string& label) {
  // Stable small integers; the bridge stores but never consumes them
  // (get_ast_root_action.py:51), so this only needs determinism.
  static const std::vector<std::string> known = {
      "CompilationUnit", "PackageDeclaration", "ImportDeclaration",
      "TypeDeclaration", "EnumDeclaration", "EnumConstantDeclaration",
      "AnnotationTypeDeclaration", "AnnotationTypeMemberDeclaration",
      "AnonymousClassDeclaration", "TypeParameter", "FieldDeclaration",
      "MethodDeclaration", "SingleVariableDeclaration",
      "VariableDeclarationFragment", "VariableDeclarationStatement",
      "VariableDeclarationExpression", "Initializer", "Block",
      "ExpressionStatement", "IfStatement", "ForStatement",
      "EnhancedForStatement", "WhileStatement", "DoStatement", "TryStatement",
      "CatchClause", "SwitchStatement", "SwitchCase", "BreakStatement",
      "ContinueStatement", "ReturnStatement", "ThrowStatement",
      "SynchronizedStatement", "LabeledStatement", "AssertStatement",
      "TypeDeclarationStatement", "ConstructorInvocation",
      "SuperConstructorInvocation", "MethodInvocation",
      "SuperMethodInvocation", "ClassInstanceCreation", "FieldAccess",
      "SuperFieldAccess", "ArrayAccess", "ArrayCreation", "ArrayInitializer",
      "Assignment", "InfixExpression", "PrefixExpression",
      "PostfixExpression", "ConditionalExpression", "CastExpression",
      "InstanceofExpression", "ParenthesizedExpression", "TypeLiteral",
      "SimpleType", "QualifiedType", "ParameterizedType", "ArrayType",
      "WildcardType", "UnionType", "MarkerAnnotation", "NormalAnnotation",
      "SingleMemberAnnotation", "MemberValuePair", "SimpleName",
      "QualifiedName", "PrimitiveType", "Modifier", "NumberLiteral",
      "StringLiteral", "CharacterLiteral", "BooleanLiteral", "NullLiteral",
      "ThisExpression", "EmptyStatement", "LambdaExpression",
      "ExpressionMethodReference"};
  for (size_t i = 0; i < known.size(); ++i)
    if (known[i] == label) return static_cast<int>(i);
  return 999;
}

void node_json(const Node* n, std::ostringstream& os) {
  os << "{\"id\":" << n->id << ",\"type\":" << type_code(n->typeLabel)
     << ",\"typeLabel\":\"";
  json_escape(n->typeLabel, os);
  os << "\",\"pos\":" << n->pos << ",\"length\":" << n->length;
  if (n->has_label) {
    os << ",\"label\":\"";
    json_escape(n->label, os);
    os << "\"";
  }
  os << ",\"children\":[";
  for (size_t i = 0; i < n->children.size(); ++i) {
    if (i) os << ",";
    node_json(n->children[i], os);
  }
  os << "]}";
}

}  // namespace

std::string to_json(const Tree& t) {
  std::ostringstream os;
  os << "{\"root\":";
  node_json(t.root, os);
  os << "}";
  return os.str();
}

}  // namespace astdiff
