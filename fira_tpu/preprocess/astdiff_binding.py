"""ctypes binding for the native astdiff component.

The reference shells out to a vendored Java GumTree per chunk — two JVM
subprocess launches per update hunk (/root/reference/Preprocess/
get_ast_root_action.py:70,124). Here the C++ library is loaded once per
process and called in-process: no JVM, no fork/exec, no temp .java files.

Python surface (all return None on unparseable input, mirroring the
reference's graceful degradation at process_data_ast_parallel.py:204-217):

    tokenize(src)   -> [token_text]            (javalang.tokenizer stand-in)
    parse_json(src) -> {"root": {...}}         (`parse` CLI contract payload)
    diff_lines(a,b) -> ["Match ...", ...]      (`diff` CLI contract lines)

The CLI binary (``astdiff parse|diff``) built by the same Makefile is the
subprocess-compatible contract surface kept for differential testing against
the reference's GumTree jar.
"""

from __future__ import annotations

import ctypes
import fcntl
import json
import os
import subprocess
import threading
from typing import List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
ASTDIFF_DIR = os.path.join(_HERE, "astdiff")
LIB_PATH = os.path.join(ASTDIFF_DIR, "libastdiff.so")
CLI_PATH = os.path.join(ASTDIFF_DIR, "astdiff")

_SOURCES = ("astdiff.hpp", "lexer.cpp", "parser.cpp", "matcher.cpp",
            "capi.cpp", "Makefile")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class AstdiffBuildError(RuntimeError):
    pass


def _stale() -> bool:
    # Both artifacts must exist and be newer than every source — the CLI is
    # the differential-testing surface and must never lag the library.
    for target in (LIB_PATH, CLI_PATH):
        if not os.path.exists(target):
            return True
        mtime = os.path.getmtime(target)
        if any(os.path.getmtime(os.path.join(ASTDIFF_DIR, s)) > mtime
               for s in _SOURCES
               if os.path.exists(os.path.join(ASTDIFF_DIR, s))):
            return True
    return False


def build(force: bool = False) -> str:
    """Build libastdiff.so (and the CLI) if missing or older than sources.

    Safe under concurrent builders (a multiprocessing worker pool all hitting
    first use): an exclusive file lock serializes the compiles, and each
    compile writes to a private temp name then atomically renames into place,
    so no process can ever dlopen a half-written library.
    """
    with _lock:
        if not (force or _stale()):
            return LIB_PATH
        lock_path = os.path.join(ASTDIFF_DIR, ".build.lock")
        with open(lock_path, "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                if not (force or _stale()):  # a peer built it while we waited
                    return LIB_PATH
                tmp_lib = f"libastdiff.so.{os.getpid()}.tmp"
                tmp_bin = f"astdiff.{os.getpid()}.tmp"
                proc = subprocess.run(
                    ["make", "-C", ASTDIFF_DIR, f"LIB={tmp_lib}",
                     f"BIN={tmp_bin}"],
                    capture_output=True, text=True)
                if proc.returncode != 0:
                    raise AstdiffBuildError(
                        f"astdiff build failed:\n{proc.stdout}\n{proc.stderr}")
                os.replace(os.path.join(ASTDIFF_DIR, tmp_lib), LIB_PATH)
                os.replace(os.path.join(ASTDIFF_DIR, tmp_bin), CLI_PATH)
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
    return LIB_PATH


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    build()
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(LIB_PATH)
            for fn in ("astdiff_parse", "astdiff_tokenize"):
                getattr(lib, fn).argtypes = [ctypes.c_char_p]
                getattr(lib, fn).restype = ctypes.c_void_p
            lib.astdiff_diff.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            lib.astdiff_diff.restype = ctypes.c_void_p
            lib.astdiff_free.argtypes = [ctypes.c_void_p]
            lib.astdiff_free.restype = None
            _lib = lib
    return _lib


def _take(lib: ctypes.CDLL, ptr: Optional[int]) -> Optional[str]:
    """Copy a malloc'd C string into Python and free it."""
    if not ptr:
        return None
    try:
        return ctypes.string_at(ptr).decode("utf-8", errors="replace")
    finally:
        lib.astdiff_free(ptr)


def tokenize(src: str) -> Optional[List[str]]:
    lib = _load()
    out = _take(lib, lib.astdiff_tokenize(src.encode("utf-8")))
    if out is None:
        return None
    return [t for t in out.split("\n") if t]


def parse_json(src: str) -> Optional[dict]:
    lib = _load()
    out = _take(lib, lib.astdiff_parse(src.encode("utf-8")))
    if out is None:
        return None
    try:
        return json.loads(out)
    except RecursionError:
        # The parser bounds tree depth well inside json.loads' budget, but if
        # the caller runs under a lowered recursion limit, degrade like any
        # other unparseable chunk instead of blowing up the worker.
        return None


def diff_lines(src_old: str, src_new: str) -> Optional[List[str]]:
    lib = _load()
    out = _take(lib, lib.astdiff_diff(src_old.encode("utf-8"),
                                      src_new.encode("utf-8")))
    if out is None:
        return None
    return [ln for ln in out.splitlines() if ln.strip()]
