"""Open-loop arrival schedules for the serving loop.

An OPEN-loop load generator decides every request's arrival time up
front, independent of how fast the server answers (the standard serving
methodology — a slow server does not throttle its own offered load, it
accumulates queue and the tail latencies show it; closed-loop drains
hide exactly that). Two sources:

- :func:`poisson_times` — Poisson arrivals at a configured offered rate
  (i.i.d. exponential inter-arrival gaps, seeded, deterministic);
- an arrival-trace FILE (:func:`write_trace` / :func:`read_trace`) — one
  non-decreasing arrival time per line, line ``i`` belonging to split
  position ``i``. Traces make serving runs REPLAYABLE: the equivalence
  tests (tests/test_serve.py) replay one fixed trace across replica
  counts, harvest cadences, and feeder worker counts and pin identical
  output file bytes.

Times are seconds on whatever clock the serving loop runs (wall for the
bench, virtual for deterministic replay — serve/server.py).
"""

from __future__ import annotations

import numpy as np


def poisson_times(n: int, rate: float, seed: int = 0) -> np.ndarray:
    """Arrival times (seconds, float64, non-decreasing, starting at the
    first gap) of ``n`` Poisson arrivals at ``rate`` requests/second:
    the cumulative sum of seeded i.i.d. Exp(rate) inter-arrival gaps."""
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    if rate <= 0:
        raise ValueError(f"offered rate must be > 0 requests/s, got {rate}")
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / rate, size=n))


def write_trace(path: str, times: np.ndarray) -> str:
    """Write an arrival trace: one ``%.9f`` time per line, split order."""
    arr = np.asarray(times, dtype=np.float64)
    _validate(arr, where=path)
    with open(path, "w") as f:
        for t in arr:
            f.write(f"{t:.9f}\n")
    return path


def read_trace(path: str) -> np.ndarray:
    """Read an arrival trace written by :func:`write_trace` (or by hand:
    one float per line; blank lines and ``#`` comments skipped).
    Validates non-negative, non-decreasing times — a shuffled or
    negative trace is a malformed input, not a schedule — citing the
    REAL file line (comments and blanks do not shift the blame)."""
    times = []   # (file line, value) — errors cite the actual line
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            s = raw.strip()
            if not s or s.startswith("#"):
                continue
            try:
                t = float(s)
            except ValueError:
                raise ValueError(
                    f"{path}:{ln}: {s!r} is not a float arrival time")
            if t < 0:
                raise ValueError(
                    f"{path}:{ln}: arrival times must be >= 0, got {t}")
            if times and t < times[-1][1]:
                raise ValueError(
                    f"{path}: arrival times must be non-decreasing "
                    f"(line {ln} goes backwards)")
            times.append((ln, t))
    return np.asarray([t for _ln, t in times], dtype=np.float64)


def _validate(times: np.ndarray, *, where: str) -> None:
    if times.ndim != 1:
        raise ValueError(f"{where}: arrival times must be 1-D")
    if len(times) and float(times[0]) < 0:
        raise ValueError(f"{where}: arrival times must be >= 0")
    if len(times) > 1 and np.any(np.diff(times) < 0):
        i = int(np.argmax(np.diff(times) < 0)) + 1
        raise ValueError(
            f"{where}: arrival times must be non-decreasing "
            f"(line {i + 1} goes backwards)")
