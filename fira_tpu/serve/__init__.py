"""Online serving on the slot engine (docs/SERVING.md).

The continuous-batching engine (decode/engine.py) and its replicated
fleet (parallel/fleet.py) drain a static, pre-packed corpus stream —
throughput numbers, no latency story. This package turns them into a
long-lived server: an open-loop load generator (arrivals.py — Poisson at
a configured offered rate, or a replayable arrival-trace file) feeds an
arrival-timed admission queue; the serving loop (server.py) forms prefill
batches from live arrivals, caps prefill/step interleaving with a
per-dispatch prefill budget, sheds on backpressure (bounded queue,
per-request deadlines — rejection recorded, never a hang), and meters
per-request TTFT and end-to-end latency for the p50/p99 bench
(scripts/serve_bench.py -> docs/SERVE_BENCH_r01.jsonl). With
``serve_tiers=prefill-pool`` (disagg.py — docs/SERVING.md
"Disaggregated tiers") prefill moves off the decode replicas entirely:
a spawn-pool of prefill worker processes ships seat-ready artifacts
over a pipe/shared-memory transport and decode admits every request
through the prefix cache's all-hit path.
"""

from fira_tpu.serve.arrivals import (poisson_times, read_trace,  # noqa: F401
                                     write_trace)
from fira_tpu.serve.disagg import (PrefillTier, TierStats,  # noqa: F401
                                   disagg_errors)
from fira_tpu.serve.server import (RequestRecord, ServeStats,  # noqa: F401
                                   serve_errors, serve_split)
