"""Disaggregated prefill/decode serving tiers (docs/SERVING.md
"Disaggregated tiers").

DistServe-style process split (OSDI'24; PAPERS.md): prefill and decode
interfere when they share one runtime — every prefill admitted
mid-stream stalls the seated slots' next decode step, which is exactly
the ``serve_prefill_budget`` tradeoff the in-process serve loop carries.
This module deletes that tradeoff structurally. A pool of **prefill
worker processes** (the ``ingest_exec=process`` spawn-pool template —
spawn, never fork: the parent runs live feeder/engine threads) each
holds its OWN jax runtime + params and computes per-request prefill
artifacts — exactly the prefix-cache payload (encoder output / one-beam
cross K/V / copy-head src projections, per-row content checksum,
tier-namespaced digest) — and ships them to the decode tier over a
process transport: pipe messages for control + small rows, shared-memory
segments for large artifact blobs. The decode side seeds every replica's
prefix cache (``SlotEngine.cache_put``) so requests admit through the
existing ALL-HIT cache path: host assemble + one device_put, ZERO
prefill dispatches on the decode replica, post-warmup.

Contract (pinned by tests/test_disagg.py and the check.sh disagg smoke
leg): trace-replay through the disaggregated path is byte-identical to
in-process serve, invariant to prefill-worker count and transport
interleaving; zero post-warmup retraces on the decode tier; every
shipped row is checksum-verified at seat (a corrupt transport — the
``disagg.transport`` fault site — re-prefills, never a wrong answer).
Lifecycle rides the existing retirement machinery: a dead worker process
retires and its in-flight work resubmits to survivors; all-workers-lost
is a RECORDED fallback to in-process prefill (``TierStats.fallback``),
never a hang.

This module imports no JAX at module level: it is the spawn-entry module
for the worker children, and the child pins ``JAX_PLATFORMS`` from the
parent's backend BEFORE its first jax import (the TPU-tunnel guard —
fira_tpu/utils/backend_guard.py).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.decode import prefix_cache as prefix_cache_lib
from fira_tpu.robust import faults as faults_lib

TIERS = ("off", "prefill-pool")

# rows whose packed artifact blob crosses this ship via a shared-memory
# segment (one segment per result message, parent attaches/copies/
# unlinks); smaller results ride the pipe inline. Module-level so tests
# can pin either transport (both are checksum-verified identically).
SHM_MIN_BYTES = 1 << 18

# a digest may be submitted to the pool at most this many times ON TOP
# of cfg.robust_retries before the tier gives it up to the decode
# replica's own in-process prefill (the per-request fallback — bounded,
# so a persistently-corrupting transport degrades, never livelocks)
_BASE_ATTEMPTS = 1


def disagg_errors(cfg: FiraConfig) -> List[str]:
    """Parse-time validation for the disaggregated-tier knobs (CLI exit
    2 — the named-knob contract every serving knob meets)."""
    errs: List[str] = []
    if cfg.serve_tiers not in TIERS:
        errs.append(
            f"serve_tiers {cfg.serve_tiers!r} is not one of {TIERS}; "
            f"see docs/SERVING.md 'Disaggregated tiers'")
    if cfg.serve_tiers != "off":
        if not cfg.decode_engine:
            errs.append(
                "serve_tiers=prefill-pool requires decode_engine: the "
                "decode tier seats shipped artifacts through the slot "
                "engine's cache-admission path")
        if not cfg.prefix_cache:
            errs.append(
                "serve_tiers=prefill-pool requires prefix_cache: shipped "
                "artifacts enter decode replicas through the prefix "
                "cache (the all-hit admission path)")
    if cfg.prefill_workers < 1:
        errs.append(
            f"prefill_workers must be >= 1, got {cfg.prefill_workers}")
    if cfg.serve_artifact_budget_mb < 0:
        errs.append(
            f"serve_artifact_budget_mb must be >= 0 (0 = unbounded), "
            f"got {cfg.serve_artifact_budget_mb}")
    return errs


# --------------------------------------------------------------------------
# worker child
# --------------------------------------------------------------------------

def _ship_result(conn, seq: int, rows) -> None:
    """Ship one computed group back: ``rows`` is
    ``[(digest, checksum, payload_dict), ...]``. Small groups ride the
    pipe; large ones pack every array into ONE shared-memory segment and
    send (name, dtype, shape, offset) metadata — the parent copies out
    and unlinks. The checksum covers the payload CONTENT either way, so
    the verify-at-seat contract is transport-agnostic."""
    total = sum(prefix_cache_lib.payload_nbytes(p) for _d, _c, p in rows)
    if total < SHM_MIN_BYTES:
        conn.send(("result", seq, rows, None))
        return
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(create=True, size=max(1, total))
    try:
        # the PARENT owns unlink (it outlives this copy): deregister the
        # segment from the child's resource tracker so child exit does
        # not double-unlink / warn about a segment that is not leaked
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    off = 0
    meta = []
    for d, c, p in rows:
        fields = []
        for name in sorted(p):
            a = np.ascontiguousarray(p[name])
            nb = int(a.nbytes)  # firacheck: allow[HOST-SYNC] host numpy payload being packed into the shm segment — no device value in the child's ship path
            shm.buf[off:off + nb] = a.tobytes()
            fields.append((name, str(a.dtype), tuple(a.shape), off, nb))
            off += nb
        meta.append((d, c, fields))
    name = shm.name
    shm.close()
    conn.send(("result", seq, meta, name))


def _worker_main(conn, init: Dict) -> None:
    """Prefill-worker entry (spawn child). Pins the jax platform from
    the parent's backend BEFORE the first jax import, builds a real
    SlotEngine from the shipped cfg + host params (byte-identity: the
    worker's prefill IS the decode engine's ``_prefill`` program), warms
    its prefill family once per bucket, then serves ``work`` messages
    until ``stop``. An injected ``disagg.worker`` raise exits the
    PROCESS — deliberately: worker death is the failure mode under
    test, and the parent's sweep retires + resubmits."""
    os.environ.setdefault("JAX_PLATFORMS", init["platform"])
    import jax
    from fira_tpu.decode.engine import SlotEngine
    from fira_tpu.model.model import FiraModel

    cfg: FiraConfig = init["cfg"]
    wid: int = init["worker_id"]
    templates: Dict[int, Dict] = init["templates"]
    inj = faults_lib.injector_from(cfg)
    eng = SlotEngine(FiraModel(cfg), init["params"], cfg,
                     slots=max(1, cfg.test_batch_size))

    def _prefill_group(bucket: int, rows) -> List[Tuple]:
        tmpl = templates[bucket]
        batch = {k: np.array(v) for k, v in tmpl.items()  # firacheck: allow[HOST-SYNC] host-side wire assembly from the host template — the single H2D device_put below is the boundary
                 if not k.startswith("_")}
        for j, (_d, rh) in enumerate(rows):
            for k in batch:
                batch[k][j] = rh[k][0]
        chunk = eng._prefill(eng.params, jax.device_put(batch))
        chunk_host = {f: np.asarray(jax.device_get(chunk[f]))  # firacheck: allow[HOST-SYNC] the worker child's whole job is materializing prefill artifacts on host for transport; this D2H is the product, not a stall
                      for f in eng._artifact_fields()}
        entries = prefix_cache_lib.extract_payloads(
            chunk_host, list(range(len(rows))), cfg.beam_size)
        return [(rows[j][0], prefix_cache_lib.payload_checksum(entries[j]),
                 entries[j]) for j in range(len(rows))]

    # prewarm the prefill program per bucket and report the measured
    # per-row artifact footprint — the parent's backpressure unit
    est: Dict[int, int] = {}
    for b in sorted(templates):
        wire = {k: np.array(v) for k, v in templates[b].items()  # firacheck: allow[HOST-SYNC] prewarm-time host wire assembly, once per bucket before any request exists
                if not k.startswith("_")}
        chunk = eng._prefill(eng.params, jax.device_put(wire))
        chunk_host = {f: np.asarray(jax.device_get(chunk[f]))  # firacheck: allow[HOST-SYNC] prewarm-time artifact sizing for the ready handshake (once per bucket, before any request exists)
                      for f in eng._artifact_fields()}
        entry = prefix_cache_lib.extract_payloads(
            chunk_host, [0], cfg.beam_size)[0]
        est[b] = prefix_cache_lib.payload_nbytes(entry)
    conn.send(("ready", wid, est))

    while True:
        msg = conn.recv()
        if msg[0] == "stop":
            break
        _kind, seq, bucket, rows = msg
        if inj is not None:
            try:
                inj.check("disagg.worker", key=f"w{wid}:{seq}")
            except faults_lib.InjectedFault:
                # worker DEATH, quietly (no traceback spew into chaos
                # runs): the parent sees the pipe close / dead process
                conn.close()
                os._exit(17)
        _ship_result(conn, seq, _prefill_group(bucket, rows))
    conn.close()


def _unpack_rows(rows, shm_name: Optional[str]) -> List[Tuple]:
    """Parent-side receive: inline rows pass through; shared-memory rows
    copy out of the segment, which is then closed AND unlinked (the
    parent owns the segment's end of life)."""
    if shm_name is None:
        return list(rows)
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        out = []
        for d, c, fields in rows:
            p = {}
            for name, dt, shape, off, nb in fields:
                dtype = np.dtype(dt)
                p[name] = np.frombuffer(
                    shm.buf, dtype=dtype, count=nb // dtype.itemsize,
                    offset=off).reshape(shape).copy()
            out.append((d, c, p))
        return out
    finally:
        shm.close()
        shm.unlink()


def _discard_shm(shm_name: Optional[str]) -> None:
    """Unlink a segment whose message was dropped (transport fault or
    tier shutdown) without reading it — the no-leak path."""
    if shm_name is None:
        return
    from multiprocessing import shared_memory

    try:
        shm = shared_memory.SharedMemory(name=shm_name)
        shm.close()
        shm.unlink()
    except Exception:
        pass


# --------------------------------------------------------------------------
# parent-side tier
# --------------------------------------------------------------------------

@dataclasses.dataclass
class TierStats:
    """Prefill-tier observability (serve_metrics.json ``tiers`` block —
    present only when tiers ran, so tier-less summaries stay
    byte-stable). Every field lands in :meth:`summary`."""

    workers: int = 0
    workers_lost: int = 0
    fallback: bool = False
    fallback_reason: str = ""
    groups_submitted: int = 0
    rows_submitted: int = 0
    rows_delivered: int = 0
    rows_resubmitted: int = 0
    rows_given_up: int = 0
    transport_msgs_lost: int = 0
    transport_integrity_drops: int = 0
    shm_segments: int = 0
    artifact_bytes: int = 0
    inflight_bytes: int = 0
    peak_inflight_bytes: int = 0
    peak_backlog: int = 0
    prefill_busy_s: float = 0.0
    rows_by_worker: Dict[int, int] = dataclasses.field(default_factory=dict)

    def summary(self) -> Dict:
        return {
            "workers": self.workers,
            "workers_lost": self.workers_lost,
            "fallback": self.fallback,
            "fallback_reason": self.fallback_reason,
            "groups_submitted": self.groups_submitted,
            "rows_submitted": self.rows_submitted,
            "rows_delivered": self.rows_delivered,
            "rows_resubmitted": self.rows_resubmitted,
            "rows_given_up": self.rows_given_up,
            "transport_msgs_lost": self.transport_msgs_lost,
            "transport_integrity_drops": self.transport_integrity_drops,
            "shm_segments": self.shm_segments,
            "artifact_bytes": self.artifact_bytes,
            "inflight_bytes": self.inflight_bytes,
            "peak_inflight_bytes": self.peak_inflight_bytes,
            "peak_backlog": self.peak_backlog,
            "prefill_busy_s": self.prefill_busy_s,
            "rows_by_worker": {str(k): v
                               for k, v in sorted(self.rows_by_worker.items())},
        }


@dataclasses.dataclass
class _Group:
    """One submitted work item: a same-bucket batch of queue entries."""

    seq: int
    bucket: int
    entries: List[object]      # serve/server._Queued
    bytes_est: int
    submit_t: float


class _Worker:
    """One prefill worker process + its pipe end, parent side."""

    def __init__(self, wid: int, proc, conn) -> None:
        self.wid = wid
        self.proc = proc
        self.conn = conn
        self.ready = False
        self.retired = False
        self.row_bytes: Dict[int, int] = {}
        self.inflight: Dict[int, _Group] = {}

    @property
    def live(self) -> bool:
        return not self.retired and self.proc.is_alive()


class PrefillTier:
    """The parent-side prefill pool: submission (``service`` — pump the
    serve queue into worker batches under the in-flight byte budget),
    delivery (drain results, checksum-verify, seed every decode
    replica's cache), and lifecycle (dead worker => retire + resubmit to
    survivors; all lost => recorded in-process fallback). Stateless
    about queue membership on purpose: requests STAY in the serve
    loop's admission queue (held by ``holds``) until their artifacts
    land, so sheds/promotions/retirements keep their existing semantics
    untouched."""

    def __init__(self, params_host, cfg: FiraConfig, *,
                 templates: Dict[int, Dict], faults=None) -> None:
        import multiprocessing

        self.cfg = cfg
        self._bs = max(1, int(cfg.test_batch_size))
        self._budget = int(cfg.serve_artifact_budget_mb) * (1 << 20)
        self._max_attempts = _BASE_ATTEMPTS + max(0, int(cfg.robust_retries))
        self._watchdog_s = float(cfg.dispatch_watchdog_s or 0.0)
        self._faults = faults
        self.stats = TierStats(workers=int(cfg.prefill_workers))
        self._pending: Dict[str, int] = {}     # digest -> owning seq
        self._attempts: Dict[str, int] = {}    # digest -> submit count
        self._given_up: set = set()
        self._first_seen: Dict[str, float] = {}
        self._inflight_bytes = 0
        self._seq = 0
        self._rr = 0
        self._dead = False
        self._closed = False
        platform = os.environ.get("JAX_PLATFORMS", "")
        if not platform:
            import jax
            platform = jax.default_backend()
        from fira_tpu.analysis.sanitizer import leak_guard
        self._leaks = leak_guard()
        if self._leaks is not None:
            self._leaks.note_acquire(
                "pool", f"PrefillTier@{id(self):x}",
                what=f"prefill worker pool ({cfg.prefill_workers} procs)")
        # spawn, never fork: the parent runs live feeder/engine threads
        # (the ingest_exec=process rule) and each child needs a FRESH
        # jax runtime of its own
        ctx = multiprocessing.get_context("spawn")
        self._workers: List[_Worker] = []
        for wid in range(cfg.prefill_workers):
            parent_conn, child_conn = ctx.Pipe()
            init = {"cfg": cfg, "params": params_host,
                    "templates": templates, "platform": platform,
                    "worker_id": wid}
            proc = ctx.Process(target=_worker_main,
                               args=(child_conn, init), daemon=True,
                               name=f"fira-prefill-w{wid}")
            proc.start()
            child_conn.close()
            self._workers.append(_Worker(wid, proc, parent_conn))

    # --- scheduling surface (serve/server.ServeLoop) --------------------

    @property
    def alive(self) -> bool:
        return not self._dead and not self._closed

    def holds(self, digest) -> bool:
        """True when the tier owns prefill for this digest: the serve
        loop holds such misses in the queue (NEVER dispatching a decode-
        tier prefill for them) until delivery flips their admission to a
        cache hit. False once the tier is dead or the digest exhausted
        its resubmit budget — the recorded in-process fallback."""
        return self.alive and digest is not None \
            and digest not in self._given_up

    def service(self, queue, engines) -> None:
        """One scheduler-round tick: sweep dead workers, drain every
        available result, then pump fresh queue misses into worker
        batches. Called from the serve loop's round head — all host
        work, no jax dispatch, so the decode tier's round cadence is
        untouched."""
        if not self.alive:
            return
        self._sweep(engines)
        self._drain(engines)
        self._pump(queue, engines)

    def idle_wait(self, timeout: float) -> None:
        """Bounded wait for tier progress when the serve loop has
        NOTHING dispatchable (every queued request is tier-held): block
        on the worker pipes up to ``timeout`` instead of busy-spinning
        the scheduler. Wakes early on any message (ready/result) or
        worker death (pipe close wakes the wait too)."""
        if not self.alive:
            return
        busy = any(w.inflight for w in self._workers) \
            or bool(self._pending) or not all(
                w.ready for w in self._workers if w.live)
        conns = [w.conn for w in self._workers if not w.retired]
        if not busy or not conns:
            return
        from multiprocessing import connection
        # bounded idle wait while ZERO dispatchable work exists (every
        # queued request is tier-held awaiting a worker result); the
        # alternative is a hot busy-spin of the scheduler round — same
        # contract as the all-replicas-lost 10ms beat
        connection.wait(conns, timeout)

    # --- internals ------------------------------------------------------

    def _sweep(self, engines) -> None:
        now = time.perf_counter()
        for w in self._workers:
            if w.retired:
                continue
            if not w.proc.is_alive():
                self._retire_worker(w, "process died")
            elif self._watchdog_s and w.inflight:
                oldest = min(g.submit_t for g in w.inflight.values())
                if now - oldest > self._watchdog_s:
                    self._retire_worker(
                        w, f"work item exceeded the "
                           f"{self._watchdog_s:.1f}s dispatch watchdog")
        if not any(w.live for w in self._workers) and not self._dead:
            self._dead = True
            self.stats.fallback = True
            self.stats.fallback_reason = (
                "all prefill workers lost; decode tier resumed "
                "in-process prefill")

    def _retire_worker(self, w: _Worker, reason: str) -> None:
        if w.retired:
            return
        w.retired = True
        self.stats.workers_lost += 1
        for group in w.inflight.values():
            # requeue to survivors: the digests simply leave the pending
            # set — the entries never left the serve queue, so the next
            # pump resubmits them to whichever workers remain
            self._inflight_bytes -= group.bytes_est
            for e in group.entries:
                if self._pending.pop(e.digest, None) is not None:
                    self.stats.rows_resubmitted += 1
        w.inflight.clear()
        try:
            w.conn.close()
        except Exception:
            pass
        if w.proc.is_alive():
            w.proc.terminate()
        self.stats.inflight_bytes = self._inflight_bytes

    def _drain(self, engines) -> None:
        for w in self._workers:
            if w.retired:
                continue
            while True:
                try:
                    if not w.conn.poll(0):
                        break
                    msg = w.conn.recv()
                except (EOFError, OSError, BrokenPipeError):
                    self._retire_worker(w, "transport connection lost")
                    break
                self._handle(w, msg, engines)

    def _handle(self, w: _Worker, msg, engines) -> None:
        if msg[0] == "ready":
            _kind, _wid, est = msg
            w.ready = True
            w.row_bytes = dict(est)
            return
        if msg[0] != "result":
            return
        _kind, seq, rows, shm_name = msg
        recv_t = time.perf_counter()
        group = w.inflight.pop(seq, None)
        if group is not None:
            self._inflight_bytes -= group.bytes_est
            self.stats.inflight_bytes = self._inflight_bytes
            self.stats.prefill_busy_s += recv_t - group.submit_t
        if self._faults is not None \
                and self._faults.armed("disagg.transport"):
            try:
                self._faults.check("disagg.transport", key=seq)
            except faults_lib.InjectedFault:
                # the message is LOST in transport: discard it (and its
                # segment) — the digests leave pending and the next pump
                # resubmits them; bytes-identical output, later
                _discard_shm(shm_name)
                self.stats.transport_msgs_lost += 1
                if group is not None:
                    for e in group.entries:
                        if self._pending.pop(e.digest, None) is not None:
                            self.stats.rows_resubmitted += 1
                return
        try:
            unpacked = _unpack_rows(rows, shm_name)
        except (OSError, ValueError):
            # segment vanished (e.g. producer died mid-ship): same
            # degrade as a lost message
            self.stats.transport_msgs_lost += 1
            if group is not None:
                for e in group.entries:
                    if self._pending.pop(e.digest, None) is not None:
                        self.stats.rows_resubmitted += 1
            return
        if shm_name is not None:
            self.stats.shm_segments += 1
        for i, (digest, checksum, payload) in enumerate(unpacked):
            if self._faults is not None:
                payload = self._faults.corrupt("disagg.transport",
                                               f"{seq}:{i}", payload)
            if prefix_cache_lib.payload_checksum(payload) != checksum:
                # checksum caught a scrambled row at the seat boundary:
                # drop it and re-prefill — NEVER a wrong answer
                self.stats.transport_integrity_drops += 1
                if self._pending.pop(digest, None) is not None:
                    self.stats.rows_resubmitted += 1
                continue
            nb = prefix_cache_lib.payload_nbytes(payload)
            for eng in engines:
                eng.cache_put(digest, payload)
            self._pending.pop(digest, None)
            self.stats.rows_delivered += 1
            self.stats.artifact_bytes += nb
            self.stats.rows_by_worker[w.wid] = \
                self.stats.rows_by_worker.get(w.wid, 0) + 1
            if group is not None and i < len(group.entries):
                rec = group.entries[i].record
                if rec.status == "queued":
                    rec.transport_s = recv_t - group.submit_t
                    rec.artifact_bytes = nb

    def _pump(self, queue, engines) -> None:
        now = time.perf_counter()
        cand = []
        for e in queue:
            d = e.digest
            if d is None or d in self._pending or d in self._given_up \
                    or e.record.status != "queued":
                continue
            if d not in self._first_seen:
                self._first_seen[d] = now
            if engines and all(eng.cache_contains(d) for eng in engines):
                continue
            if self._attempts.get(d, 0) >= self._max_attempts:
                self._given_up.add(d)
                self.stats.rows_given_up += 1
                continue
            cand.append(e)
        self.stats.peak_backlog = max(self.stats.peak_backlog, len(cand))
        ready = [w for w in self._workers if w.ready and w.live]
        if not ready:
            return
        while cand:
            bucket = cand[0].bucket
            take, rest = [], []
            for e in cand:
                if e.bucket == bucket and len(take) < self._bs:
                    take.append(e)
                else:
                    rest.append(e)
            cand = rest
            est = len(take) * max(
                1, ready[0].row_bytes.get(bucket, SHM_MIN_BYTES))
            if self._budget and self._inflight_bytes \
                    and self._inflight_bytes + est > self._budget:
                # backpressure: the in-flight artifact budget is spent —
                # wait for deliveries. A single group alone still ships
                # (inflight==0 path), the same degrade rule as the
                # prefix cache's byte cap.
                break
            w = ready[self._rr % len(ready)]
            self._rr += 1
            seq = self._seq
            self._seq += 1
            rows = [(e.digest,
                     {k: v for k, v in e.host.items()
                      if not k.startswith("_")}) for e in take]
            try:
                w.conn.send(("work", seq, bucket, rows))
            except (OSError, BrokenPipeError, ValueError):
                self._retire_worker(w, "submit failed")
                ready = [x for x in self._workers if x.ready and x.live]
                if not ready:
                    return
                cand = take + cand
                continue
            group = _Group(seq, bucket, take, est, now)
            w.inflight[seq] = group
            self._inflight_bytes += est
            self.stats.inflight_bytes = self._inflight_bytes
            self.stats.peak_inflight_bytes = max(
                self.stats.peak_inflight_bytes, self._inflight_bytes)
            self.stats.groups_submitted += 1
            self.stats.rows_submitted += len(take)
            for e in take:
                self._pending[e.digest] = seq
                self._attempts[e.digest] = \
                    self._attempts.get(e.digest, 0) + 1
                rec = e.record
                rec.prefill_queue_s = now - self._first_seen[e.digest]

    def close(self) -> None:
        """Tear the pool down: best-effort drain of already-shipped
        results first (their shared-memory segments must be unlinked —
        the no-leak path the RES-LEAK sanitizer pins), then stop + join
        every worker, terminating stragglers."""
        if self._closed:
            return
        self._closed = True
        for w in self._workers:
            if w.retired:
                continue
            try:
                while w.conn.poll(0):
                    msg = w.conn.recv()
                    if msg and msg[0] == "result":
                        _discard_shm(msg[3])
            except Exception:
                pass
            try:
                w.conn.send(("stop",))
            except Exception:
                pass
        for w in self._workers:
            if not w.retired:
                w.proc.join(timeout=5.0)
                if w.proc.is_alive():
                    w.proc.terminate()
                    w.proc.join(timeout=1.0)
            try:
                w.conn.close()
            except Exception:
                pass
        if self._leaks is not None:
            self._leaks.note_release("pool", f"PrefillTier@{id(self):x}")

    def __enter__(self) -> "PrefillTier":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
