"""Arrival-timed serving loop over the slot engine (docs/SERVING.md).

The drain drivers (decode/runner.py, parallel/fleet.py) hand the engine a
pre-packed corpus stream and measure commits/s on the drained batch. This
module is the ROADMAP-item-1 other half: a long-lived SERVER under
open-loop load, where requests arrive over time (serve/arrivals.py), the
scheduler refills slots from live arrivals, and the interesting numbers
are p50/p99 TTFT and end-to-end latency against offered rate — the
Orca/vLLM serving regime, not the batch-job regime.

One scheduler round (``ServeLoop._round``), round-robined over the
engine replicas exactly like parallel/fleet.py:

1. **poll arrivals** — every request whose arrival time has passed moves
   into the admission queue (bounded by ``cfg.serve_queue_cap``; an
   arrival that finds it full is SHED immediately — rejection recorded,
   never a hang). Request payloads are pre-assembled ahead of time by the
   async Feeder (one single-row ``make_batch`` task per request, split
   order), so admission never blocks on host assembly. With
   ``cfg.prefix_cache`` armed, an arrival byte-identical to a request
   already in flight (same worker-stamped content digest —
   decode/prefix_cache.py) COALESCES onto that leader instead of taking
   a queue slot: one decode, N output positions at the leader's harvest,
   each request keeping its own arrival/deadline/TTFT stamps. A shed
   follower detaches without killing the leader's seat; a shed leader
   hands its group to the oldest surviving follower (promotion).
2. **shed deadlines** — queued requests older than
   ``cfg.serve_deadline_steps`` step dispatches are shed (a request that
   exhausted its whole deadline without being seated cannot answer in
   time; seated requests always run to harvest and late completions are
   flagged, not killed).
3. **admit** — up to ``cfg.serve_prefill_budget`` prefill dispatches PER
   REPLICA: the head-of-queue request's bucket is flushed into one packed
   batch (up to ``test_batch_size`` same-bucket requests in arrival
   order, padded with invalid rows) and prefilled on the claiming
   replica. The budget is the latency-aware refill knob: every prefill
   dispatched here stalls the seated slots' next decode step, so a small
   budget bounds the stall seated requests pay per new admission and a
   large one trades their tail latency for admission throughput.
4. **refill / step / harvest** — the engine's own steppable pieces,
   unchanged: every live replica's step is dispatched before any harvest
   readback; harvested samples are cooked/written through the same
   position-keyed ordered writer as drain mode.

Equivalence contract (tests/test_serve.py): on a REPLAYED arrival trace
with no shedding, output file bytes are IDENTICAL to drain-mode decode —
per-sample beam math is batch-composition-invariant (every batched op is
row-wise; the contract decode/engine.py's bit-exactness tests pin), and
the writer keys by split position — and invariant to replica count,
harvest cadence, and feeder worker count, with zero post-warmup retraces
under the same declared (geometry x {prefill, step, insert, harvest})
program family: serve-mode batches reuse the drain packer's exact
geometries and batch size, so no new program ever compiles.

Clocks: ``wall`` (the bench — arrivals are paced in real time and idle
waits sleep) or ``virtual`` (replay — time advances by a fixed cost per
prefill/step dispatch and jumps across idle gaps), both observing
latencies only at dispatch/harvest boundaries, which is what the host
can honestly see.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from fira_tpu.analysis import sanitizer
from fira_tpu.config import FiraConfig
from fira_tpu.data import buckets as buckets_lib
from fira_tpu.data.dataset import FiraDataset
from fira_tpu.data.feeder import Feeder
from fira_tpu.decode import paging
from fira_tpu.decode.engine import SlotEngine
from fira_tpu.decode.runner import output_name, sample_emitter
from fira_tpu.decode.stream import OrderedStreamWriter
from fira_tpu.model.model import FiraModel
from fira_tpu.robust import faults as faults_lib
from fira_tpu.robust.watchdog import WatchdogTimeout, run_with_watchdog
from fira_tpu.serve import disagg as disagg_lib

# serve_metrics snapshot cadence: the partial artifact refreshes every
# this many scheduler rounds (plus once at startup and once on abort),
# so a SIGKILL at any point leaves a recent, valid-JSON snapshot
SNAPSHOT_EVERY_ROUNDS = 16

# prefix-cache miss micro-batching window (rounds): with the cache ON,
# cache hits admit for free and drain the queue fast, so the misses left
# behind would otherwise dispatch as fragmentary prefill batches — the
# dispatches the cache exists to save. Once the cache is actually
# serving hits (repeated traffic; cold streams keep legacy admission),
# a partial miss group WAITS (returned to the queue head) until it
# fills, its head has waited this many step-dispatch rounds, or the
# claiming replica would otherwise idle — a bounded dynamic-batching
# delay, recorded honestly in the latency stamps. Cache off: never
# holds (byte-identical legacy admission).
MISS_HOLD_ROUNDS = 16


# --------------------------------------------------------------------------
# parse-time knob validation (CLI exit 2 — the serving twin of
# parallel.mesh.divisibility_errors / decode.paging.paging_errors)
# --------------------------------------------------------------------------

def serve_errors(cfg: FiraConfig, *, trace: bool = False) -> List[str]:
    """Named-knob serving admission check. ``trace``: an arrival-trace
    file was given (the offered-rate knob is then unused)."""
    errs: List[str] = []
    if cfg.serve_rate < 0:
        errs.append(f"serve_rate {cfg.serve_rate} must be >= 0 requests/s")
    elif not trace and cfg.serve_rate == 0:
        errs.append(
            "serve_rate must be > 0 requests/s when no arrival trace is "
            "given (the open-loop Poisson generator needs an offered rate)")
    slots, _reps = paging.resolved_slots(cfg)
    if not 1 <= cfg.serve_prefill_budget <= slots:
        errs.append(
            f"serve_prefill_budget {cfg.serve_prefill_budget} must be >= 1 "
            f"and <= the per-replica engine slots ({slots}): it caps "
            f"prefill dispatches interleaved between step dispatches, and "
            f"a budget past the slot count can never seat more rows")
    if cfg.serve_deadline_steps < 0:
        errs.append(
            f"serve_deadline_steps {cfg.serve_deadline_steps} must be 0 "
            f"(no deadline) or >= 1: a request cannot complete in less "
            f"than one step dispatch")
    if cfg.serve_queue_cap < 0:
        errs.append(
            f"serve_queue_cap {cfg.serve_queue_cap} must be 0 (unbounded) "
            f"or >= 1 queued request")
    return errs


# --------------------------------------------------------------------------
# clocks
# --------------------------------------------------------------------------

class VirtualClock:
    """Deterministic replay clock: a fixed cost per prefill/step dispatch,
    idle gaps jumped. Makes a replayed trace's scheduling — hence its
    latency records — a pure function of the trace and the knobs."""

    def __init__(self, *, step_cost_s: float = 1.0,
                 prefill_cost_s: float = 1.0):
        self.step_cost_s = float(step_cost_s)
        self.prefill_cost_s = float(prefill_cost_s)
        self._now = 0.0

    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        self._now = max(self._now, float(t))

    def on_prefill(self) -> None:
        self._now += self.prefill_cost_s

    def on_step(self) -> None:
        self._now += self.step_cost_s


class WallClock:
    """Real time: arrivals are paced against the monotonic clock and an
    idle server sleeps until the next scheduled arrival (open loop — the
    generator never waits for the server, only the server for it)."""

    def __init__(self):
        self._t0 = time.perf_counter()

    def now(self) -> float:
        return time.perf_counter() - self._t0

    def advance_to(self, t: float) -> None:
        dt = float(t) - self.now()
        if dt > 0:
            time.sleep(dt)

    def on_prefill(self) -> None:
        pass

    def on_step(self) -> None:
        pass


# --------------------------------------------------------------------------
# per-request metering
# --------------------------------------------------------------------------

@dataclasses.dataclass
class RequestRecord:
    """One request's lifecycle timestamps (clock units — wall seconds or
    virtual units; every stamp is observed at a dispatch/harvest boundary,
    the only place the host honestly sees device progress)."""

    position: int            # split-local sample position
    arrival_t: float         # scheduled (open-loop) arrival time
    status: str = "pending"  # queued|staged|seated|done|shed_queue_full|
                             # shed_deadline|shed_error
    arrival_round: int = -1  # step-dispatch counter at arrival (deadline base)
    admit_t: float = math.nan       # prefill dispatched (chunk staged)
    seat_t: float = math.nan        # inserted into a slot
    first_step_t: float = math.nan  # end of its first step dispatch's
                                    # harvest phase — the TTFT stamp
    done_t: float = math.nan        # harvested (all beams settled)
    done_round: int = -1
    deadline_missed: bool = False   # completed, but past its deadline
    # poison-quarantine / retirement accounting (docs/FAULTS.md)
    error: Optional[str] = None     # recorded failure when shed_error
    retries: int = 0                # assembly/admission/prefill retries paid
    requeues: int = 0               # times re-queued off a retired replica
    # in-flight dedup (docs/DECODE_ENGINE.md "Prefix cache & dedup"): set
    # when this request coalesced onto a byte-identical leader's seat —
    # it is delivered by fan-out at the leader's harvest, keeping its OWN
    # arrival/deadline/TTFT stamps (None for leaders and cache-off runs)
    coalesced_into: Optional[int] = None
    # raw-diff ingest lifecycle stamps (docs/INGEST.md): per-stage
    # worker-side seconds (lex_s/parse_s/assemble_s), token count, the
    # deterministic-truncation record, the extraction-degradation reason,
    # and OOV fallback counts — stamped by ingest.service on the payload
    # (``_ingest``) and copied here at arrival. None on corpus-graph
    # requests, which never ran ingest.
    ingest: Optional[Dict] = None
    # disaggregated prefill-tier lifecycle stamps (docs/SERVING.md
    # "Disaggregated tiers"): wall seconds from first tier sighting to
    # pool submission (prefill_queue_s), submission to checksum-verified
    # delivery into the decode tier's caches (transport_s — the full
    # tier round trip, worker compute included), and the delivered
    # artifact's host footprint. None whenever serve_tiers=off, so
    # tier-less records stay byte-stable.
    prefill_queue_s: Optional[float] = None
    transport_s: Optional[float] = None
    artifact_bytes: Optional[int] = None

    @property
    def queue_wait_s(self) -> float:
        return self.seat_t - self.arrival_t

    @property
    def ttft_s(self) -> float:
        return self.first_step_t - self.arrival_t

    @property
    def e2e_s(self) -> float:
        return self.done_t - self.arrival_t


def _pct(values: List[float], q: float) -> Optional[float]:
    return round(float(np.percentile(np.asarray(values), q)), 6) \
        if values else None


@dataclasses.dataclass
class ServeStats:
    """Aggregate serving accounting: per-request records plus the
    scheduler counters the knee curve and the A/B rows read."""

    records: List[RequestRecord]
    completions: List[int] = dataclasses.field(default_factory=list)
    rounds: int = 0
    admits: int = 0                 # prefill batches formed from arrivals
    max_admits_per_round: int = 0   # <= serve_prefill_budget x replicas
    peak_queue_depth: int = 0
    shed_queue_full: int = 0
    shed_deadline: int = 0
    # graceful degradation (docs/FAULTS.md): requests shed with a
    # recorded error (poison quarantine / lost replicas), replicas
    # retired mid-run, and requests requeued off retired replicas
    shed_error: int = 0
    retirements: List[Dict] = dataclasses.field(default_factory=list)
    requeues: int = 0
    # self-healing + health signals (robust/recovery.py; docs/FAULTS.md
    # "Recovery contracts") — recorded UNCONDITIONALLY, recovery armed or
    # not, like feed-stall: the ROADMAP item-3 scale-up/down control
    # signal. ``replicas_alive_over_time`` appends one entry per change
    # in the live-replica set ({"round", "alive", "queue_depth",
    # "deadline_pressure"}); ``heartbeats`` stamps each replica's
    # last-dispatch round and dispatch count per scheduler round;
    # ``respawns`` records each replacement that rejoined the rotation;
    # ``admission_paused_rounds`` counts all-replicas-lost rounds spent
    # waiting on a respawn instead of shedding the remainder; ``resumed``
    # counts positions restored from a prior run's journal + output
    # prefix by ``--resume`` (never re-served, never re-emitted twice)
    replicas_alive_over_time: List[Dict] = dataclasses.field(
        default_factory=list)
    respawns: List[Dict] = dataclasses.field(default_factory=list)
    heartbeats: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    admission_paused_rounds: int = 0
    resumed: int = 0
    # in-flight dedup accounting (cfg.prefix_cache): requests coalesced
    # onto a byte-identical leader's seat, how many fan-out groups
    # delivered, and the largest group (leader + followers)
    dedup_coalesced: int = 0
    dedup_groups: int = 0
    dedup_fanout_max: int = 0
    # the ingest twin of feed-stall (docs/INGEST.md): seconds the
    # scheduler blocked waiting for a request's payload to come off the
    # feeder workers at arrival time — for raw-diff serving this is
    # exactly the ingest pipeline failing to stay ahead of arrivals
    assembly_stall_s: float = 0.0
    # REAL elapsed seconds of the whole loop run (perf_counter), the
    # stall fraction's denominator — the scheduling clock may be
    # virtual, but the stall is wall time, so the ratio must be too
    wall_s: float = 0.0
    # ingest whole-diff result-cache meter (ingest/cache.py; raw-diff
    # serving only): a zero-arg callable returning the cache's summary
    # dict, bound by serve_diffs so the final summary reads the
    # END-of-run counters — None on corpus-graph serves and with
    # cfg.ingest_cache off
    ingest_cache: Optional[object] = None
    # (workers, effective pipeline depth) of the raw-diff ingest feeder
    # — serve_diffs scales depth with the worker count past the
    # configured feeder_depth, so the actually-applied bound is
    # recorded rather than silently diverging from the knob
    ingest_pipeline: Optional[tuple] = None
    # disaggregated prefill-tier meter (serve/disagg.TierStats; docs/
    # SERVING.md "Disaggregated tiers"): a zero-arg callable returning
    # the tier's summary dict, bound by serve_split so the final
    # summary reads END-of-run counters — None with serve_tiers=off, so
    # tier-less summaries stay byte-stable (the ingest_cache pattern)
    tiers: Optional[object] = None

    def summary(self) -> Dict:
        done = [r for r in self.records if r.status == "done"]
        ttft = [r.ttft_s for r in done if not math.isnan(r.first_step_t)]
        e2e = [r.e2e_s for r in done]
        qw = [r.queue_wait_s for r in done]
        last_done = max((r.done_t for r in done), default=0.0)
        last_arr = max((r.arrival_t for r in self.records), default=0.0)
        n = len(self.records)
        return {
            "offered": n,
            "completed": len(done),
            # the harvest-order completion timeline (positions in the
            # order their beams settled) — recorded since PR 11 but only
            # serialized since the STATS-SCHEMA gate caught the drift
            "completion_order": list(self.completions),
            "shed_queue_full": self.shed_queue_full,
            "shed_deadline": self.shed_deadline,
            "shed_error": self.shed_error,
            "replica_retirements": len(self.retirements),
            "retired_replicas": [r["replica"] for r in self.retirements],
            "requeued_requests": self.requeues,
            "respawns": len(self.respawns),
            "respawned_replicas": [r["replica"] for r in self.respawns],
            "spare_attaches": sum(1 for r in self.respawns if r["spare"]),
            "replicas_alive_over_time": list(self.replicas_alive_over_time),
            # sorted: keys are inserted as replicas first dispatch, and
            # under real-clock retirement/respawn that order tracks wall
            # timing — identical request streams must serialize identical
            # metrics bytes (firacheck DET-TAINT)
            "heartbeats": {t: dict(h)
                           for t, h in sorted(self.heartbeats.items())},
            "admission_paused_rounds": self.admission_paused_rounds,
            "resumed": self.resumed,
            "request_retries": sum(r.retries for r in self.records),
            "deadline_missed": sum(r.deadline_missed for r in done),
            "dedup_coalesced": self.dedup_coalesced,
            "dedup_groups": self.dedup_groups,
            "dedup_fanout_max": self.dedup_fanout_max,
            "rounds": self.rounds,
            "admits": self.admits,
            "max_admits_per_round": self.max_admits_per_round,
            "peak_queue_depth": self.peak_queue_depth,
            "offered_rate_rps": round(n / last_arr, 4) if last_arr else None,
            "makespan_s": round(last_done, 6),
            "throughput_rps": round(len(done) / last_done, 4)
            if last_done else None,
            "p50_ttft_s": _pct(ttft, 50), "p99_ttft_s": _pct(ttft, 99),
            "p50_e2e_s": _pct(e2e, 50), "p99_e2e_s": _pct(e2e, 99),
            "mean_e2e_s": round(float(np.mean(e2e)), 6) if e2e else None,
            "p50_queue_wait_s": _pct(qw, 50), "p99_queue_wait_s": _pct(qw, 99),
            **self._ingest_summary(),
            **({"tiers": dict(self.tiers()
                              if callable(self.tiers) else self.tiers)}
               if self.tiers is not None else {}),
        }

    def _ingest_summary(self) -> Dict:
        """Aggregate raw-diff ingest stamps (docs/INGEST.md) — present
        only when any request actually ran ingest, so corpus-graph serve
        summaries stay byte-stable (the worker-count determinism
        contract: ingest stage times and the assembly stall are real
        wall seconds, honest but schedule-dependent)."""
        ing = [r.ingest for r in self.records if r.ingest]
        if not ing:
            return {}
        stage = {s: [i[s] for i in ing if s in i]
                 for s in ("lex_s", "parse_s", "assemble_s")}
        totals = [sum(i.get(s, 0.0) for s in
                      ("lex_s", "parse_s", "assemble_s")) for i in ing]
        out = {"requests_ingested": len(ing),
               "truncated": sum(1 for i in ing if i.get("truncated")),
               "degraded": sum(1 for i in ing if i.get("degraded")),
               "oov_word_fallbacks": sum(int(i.get("oov_words", 0))
                                         for i in ing),
               "oov_ast_fallbacks": sum(int(i.get("oov_ast", 0))
                                        for i in ing),
               # the fast-path hit split (docs/INGEST.md "Fast path"):
               # whole-diff hits replayed the stored payload (the
               # `cached` stamp); memo hits/misses are hunk-level AST
               # reuse INSIDE whole-diff misses — the partial-hit meter
               "cache_hits": sum(1 for i in ing if i.get("cached")),
               "memo_hits": sum(int(i.get("memo_hits", 0)) for i in ing),
               "memo_misses": sum(int(i.get("memo_misses", 0))
                                  for i in ing)}
        if self.ingest_cache is not None:
            out["cache"] = dict(self.ingest_cache()
                                if callable(self.ingest_cache)
                                else self.ingest_cache)
        if self.ingest_pipeline is not None:
            out["workers"], out["pipeline_depth"] = self.ingest_pipeline
        for s, vals in stage.items():
            out[f"mean_{s}"] = (round(float(np.mean(vals)), 9)
                                if vals else None)
        out["p50_total_s"] = _pct(totals, 50)
        out["p99_total_s"] = _pct(totals, 99)
        # the ingest twin of feed-stall: seconds the scheduler blocked at
        # arrival waiting for a payload still on the ingest workers, and
        # that stall as a fraction of the run's REAL wall time (both
        # sides perf_counter seconds — a virtual-clock makespan would be
        # a dimensionally meaningless denominator)
        out["stall_s"] = round(self.assembly_stall_s, 6)
        out["stall_frac"] = (round(self.assembly_stall_s / self.wall_s, 4)
                             if self.wall_s else None)
        return {"ingest": out}


@dataclasses.dataclass
class _Queued:
    record: RequestRecord
    host: Dict      # the request's single-row assembled batch
    bucket: int     # decode-table index (0 when unbucketed)
    digest: Optional[str] = None  # content digest (cfg.prefix_cache;
    #                               worker-stamped in _request_tasks)


# --------------------------------------------------------------------------
# the serving loop
# --------------------------------------------------------------------------

class ServeLoop:
    """Drives N engine replicas under arrival-timed admission. ``emit`` /
    ``shed`` are callbacks into the output layer (the driver below wires
    them to the ordered writer)."""

    def __init__(self, engines: Sequence[SlotEngine], cfg: FiraConfig, *,
                 arrival_times: np.ndarray, feed, table, assignment,
                 templates: Dict[int, Dict], clock, emit, shed,
                 refill_order: str = "fifo", faults=None, snapshot=None,
                 positions=None, journal=None, recovery=None, tier=None):
        self.engines = list(engines)
        self.cfg = cfg
        self.clock = clock
        self.emit = emit
        self.shed_cb = shed
        self.refill_order = refill_order
        self._table = table
        self._assignment = assignment
        self._templates = templates
        self._bs = int(cfg.test_batch_size)
        self._budget = max(1, int(cfg.serve_prefill_budget))
        self._deadline = max(0, int(cfg.serve_deadline_steps))
        self._cap = max(0, int(cfg.serve_queue_cap))
        # graceful degradation knobs (docs/FAULTS.md): the poison-request
        # retry budget, the per-dispatch wall-clock watchdog (0 = off),
        # the armed fault injector (None = off, zero overhead), and the
        # partial-metrics snapshot hook (crash contract)
        self._retries = max(0, int(cfg.robust_retries))
        self._watchdog = float(cfg.dispatch_watchdog_s)
        self._faults = faults
        self._snapshot = snapshot
        self._times = np.asarray(arrival_times, dtype=np.float64)
        self._feed_iter = iter(feed)
        self._arr_idx = 0
        self._rr = 0   # admission round-robin start (load balance)
        self._queue: "collections.deque[_Queued]" = collections.deque()
        # fleet-GLOBAL in-flight dedup (cfg.prefix_cache): digest ->
        # leader position for every non-final enqueued request, the
        # reverse map for cleanup, leader position -> coalesced follower
        # entries awaiting fan-out delivery, and followers promoted to
        # leader when their leader shed (drained into the queue outside
        # any deque walk — _drain_promotions)
        self._dedup_on = bool(cfg.prefix_cache)
        self._leaders: Dict[str, int] = {}
        self._leader_digest: Dict[int, str] = {}
        self._followers: Dict[int, List[_Queued]] = {}
        self._promoted: List[_Queued] = []
        # single-row payloads of every taken-but-unfinished request, by
        # position: the requeue source when a replica retires mid-flight
        self._payloads: Dict[int, _Queued] = {}
        self._awaiting_first_step: List[RequestRecord] = []
        self._final = 0
        # output position per arrival-stream request: identity normally;
        # a ``--resume`` run serves the not-yet-done SUFFIX of a prior
        # run's positions (robust/recovery.py), so positions are sparse
        # original indices and every position-keyed lookup goes through
        # ``_rec_by_pos`` instead of indexing the records list
        pos_arr = (np.asarray(positions, dtype=np.int64)
                   if positions is not None
                   else np.arange(len(self._times), dtype=np.int64))
        self.stats = ServeStats(records=[
            RequestRecord(position=int(p), arrival_t=float(t))
            for p, t in zip(pos_arr, self._times)])
        self._rec_by_pos: Dict[int, RequestRecord] = {
            r.position: r for r in self.stats.records}
        # self-healing + health machinery (docs/FAULTS.md "Recovery
        # contracts"): the write-ahead request journal (None = off), the
        # respawn policy (None = PR-9 retire-and-degrade), and the
        # always-on alive/heartbeat record (satellite of ROADMAP item 3)
        self._journal = journal
        self._recovery = recovery
        # disaggregated prefill tier (serve/disagg.PrefillTier, None =
        # in-process serve): while alive it OWNS every miss's prefill —
        # the admission walk holds tier-held misses queued until their
        # artifacts land in the replicas' caches and they admit as hits,
        # so the decode tier never dispatches a prefill program
        self._tier = tier
        self._shed_log: List[Dict] = []   # round-buffered shed WAL records
        self._alive_changed()

    # --- pieces ---------------------------------------------------------

    def _bucket_of(self, i: int, item) -> int:
        """A request's decode bucket: the split-wide assignment array for
        corpus-graph requests, the worker-stamped ``_bucket`` host field
        for raw-diff ingest requests (assigned per request by measured
        extents — ingest.service), 0 when unbucketed."""
        if self._assignment is not None:
            return int(self._assignment[i])
        if item.host is not None and "_bucket" in item.host:
            return int(item.host["_bucket"])
        return 0

    def _poll_arrivals(self, now: float) -> None:
        """Move every due request into the admission queue. An arrival is
        shed on the spot when the bounded queue is full, when its payload
        arrived POISONED (the feeder's per-task error channel: assembly
        failed even after its worker-side retries — recorded, never a
        re-raise), or when the serve.admit fault site rejects it past the
        retry budget."""
        while self._arr_idx < len(self._times) \
                and self._times[self._arr_idx] <= now:
            item = next(self._feed_iter)   # pre-assembled, split order
            i = self._arr_idx
            rec = self.stats.records[i]
            rec.arrival_round = self.stats.rounds
            rec.retries += int(item.retries)  # firacheck: allow[HOST-SYNC] FedBatch.retries is a host int counter stamped by the feeder worker; no device value exists here
            if item.host is not None:
                rec.ingest = item.host.get("_ingest")
            self.stats.assembly_stall_s += float(item.stall_s)  # firacheck: allow[HOST-SYNC] FedBatch.stall_s is a host perf_counter float stamped by the feeder; no device value exists here
            digest = None
            if self._dedup_on and item.host is not None:
                dl = item.host.get("_digests")
                digest = dl[0] if dl else None
            if item.error is not None:
                # poison-request quarantine: the request's assembly raised
                # (and its feeder-side retries were spent) — shed with the
                # error recorded; its output position holds an empty line
                rec.error = str(item.error)
                self._shed(rec, "shed_error")
            elif digest is not None and digest in self._leaders:
                # in-flight dedup: a byte-identical request is already
                # queued/staged/seated — COALESCE onto that leader's seat
                # instead of taking a queue slot. A coalesced request
                # consumes no seat capacity, but its payload is real host
                # memory pinned until the leader harvests, so the queue
                # cap still bounds each fan-out GROUP: a retry storm of
                # one hot digest sheds past-cap followers exactly like
                # any other flood (backpressure survives dedup).
                # Delivered by fan-out at the leader's harvest; keeps
                # its OWN arrival/deadline/TTFT stamps.
                leader = self._leaders[digest]
                if self._cap and len(self._followers.get(leader, [])) \
                        >= self._cap:
                    self._shed(rec, "shed_queue_full")
                else:
                    lrec = self._rec_by_pos[leader]
                    e = _Queued(rec, item.host, self._bucket_of(i, item),
                                digest=digest)
                    self._followers.setdefault(leader, []).append(e)
                    rec.coalesced_into = leader
                    rec.status = "queued"
                    if lrec.status in ("staged", "seated"):
                        # the leader's prefill/seat already happened: the
                        # follower inherits those milestones at coalesce
                        # time
                        rec.admit_t = now
                        rec.status = "staged"
                    if lrec.status == "seated":
                        rec.seat_t = now
                        rec.status = "seated"
                        self._awaiting_first_step.append(rec)
                    self.stats.dedup_coalesced += 1
            elif self._cap and len(self._queue) >= self._cap:
                self._shed(rec, "shed_queue_full")
            elif not self._admit_gate(rec):
                pass  # serve.admit fault past the retry budget: shed inside
            else:
                rec.status = "queued"
                if digest is not None:
                    self._leaders[digest] = rec.position
                    self._leader_digest[rec.position] = digest
                self._queue.append(_Queued(rec, item.host,
                                           self._bucket_of(i, item),
                                           digest=digest))
            self._arr_idx += 1
        self.stats.peak_queue_depth = max(self.stats.peak_queue_depth,
                                          len(self._queue))

    def _backoff(self, attempt: int) -> None:
        """Quarantine retry backoff: real sleep on the wall clock only —
        a virtual-clock replay is deterministic by construction (every
        retry is a fresh keyed draw, not a time-dependent one), so
        burning real wall time per retried fault would only slow the
        replay down."""
        if isinstance(self.clock, WallClock):
            time.sleep(faults_lib.backoff_s(attempt))

    def _admit_gate(self, rec: RequestRecord) -> bool:
        """The serve.admit fault site, with the quarantine retry policy:
        every attempt is a fresh deterministic draw, so a transient
        admission fault is absorbed by the retry budget and a persistent
        one sheds the request with its error recorded."""
        if self._faults is None or not self._faults.armed("serve.admit"):
            return True
        attempt = 0
        while True:
            try:
                self._faults.check("serve.admit")
                return True
            except Exception as e:
                if attempt < self._retries:
                    attempt += 1
                    rec.retries += 1
                    self._backoff(attempt)
                    continue
                rec.error = (f"admission rejected after {attempt + 1} "
                             f"attempt(s): {e}")
                self._shed(rec, "shed_error")
                return False

    def _shed(self, rec: RequestRecord, status: str) -> None:
        rec.status = status
        if status == "shed_queue_full":
            self.stats.shed_queue_full += 1
        elif status == "shed_deadline":
            self.stats.shed_deadline += 1
        else:
            self.stats.shed_error += 1
        self._final += 1
        self._payloads.pop(rec.position, None)
        # a shed FOLLOWER detaches from its leader's fan-out group — the
        # leader's seat is untouched (the dedup/shed contract)
        if rec.coalesced_into is not None:
            fl = self._followers.get(rec.coalesced_into)
            if fl:
                self._followers[rec.coalesced_into] = [
                    e for e in fl if e.record is not rec]
        # a shed LEADER hands its group to the oldest surviving follower:
        # the promotee re-enters the queue (via _drain_promotions — never
        # mid-walk of the deque) with its OWN arrival/deadline stamps and
        # its own byte-identical payload, and the remaining followers
        # re-point at it
        d = self._leader_digest.pop(rec.position, None)
        if d is not None:
            self._leaders.pop(d, None)
            fl = self._followers.pop(rec.position, [])
            if fl:
                head, rest = fl[0], fl[1:]
                head.record.coalesced_into = None
                self._leaders[d] = head.record.position
                self._leader_digest[head.record.position] = d
                for e in rest:
                    e.record.coalesced_into = head.record.position
                if rest:
                    self._followers[head.record.position] = rest
                self._promoted.append(head)
        self.shed_cb(rec)
        # terminal WAL record AFTER the writer took the empty line (so
        # the record never claims a position whose line missed the
        # disk); buffered and flushed once per scheduler round like the
        # admit/done batches — one fsync per round, not per shed, which
        # matters exactly on the mass-shed collapse path
        if self._journal is not None:
            self._shed_log.append({"kind": "shed", "pos": rec.position,
                                   "status": status, "error": rec.error})

    def _drain_promotions(self) -> None:
        """Enqueue followers promoted to leader by a leader shed. Runs
        OUTSIDE any queue walk (a shed mid-walk must not mutate the deque
        being iterated). A promotee whose own deadline already lapsed is
        shed here — which may promote the next follower in turn, so the
        loop runs until the promotion chain settles."""
        while self._promoted:
            e = self._promoted.pop(0)
            rec = e.record
            if self._deadline and (self.stats.rounds - rec.arrival_round
                                   >= self._deadline):
                self._shed(rec, "shed_deadline")
                continue
            rec.status = "queued"
            rec.admit_t = rec.seat_t = rec.first_step_t = math.nan
            self._queue.append(e)

    def _shed_deadlines(self) -> None:
        """Drop queued requests whose whole deadline elapsed un-seated.
        Dedup followers mirror queued semantics until their leader seats:
        a follower past its OWN deadline detaches (the leader's seat is
        never killed); once the leader is seated the group rides to
        harvest with late completions flagged per follower, exactly like
        any seated request."""
        if not self._deadline:
            return
        keep: "collections.deque[_Queued]" = collections.deque()
        for e in self._queue:
            if self.stats.rounds - e.record.arrival_round >= self._deadline:
                self._shed(e.record, "shed_deadline")
            else:
                keep.append(e)
        self._queue = keep
        self._drain_promotions()
        for leader, fl in list(self._followers.items()):
            lrec = self._rec_by_pos[leader]
            if lrec.status not in ("queued", "staged"):
                continue
            for e in list(fl):
                if (self.stats.rounds - e.record.arrival_round
                        >= self._deadline):
                    self._shed(e.record, "shed_deadline")
        self._drain_promotions()

    def _take_chunk(self, eng: SlotEngine):
        """Same-bucket requests off the queue head, arrival order
        preserved for taken AND left-behind; returns (bucket, groups).
        Cache off: one group of up to ``test_batch_size`` requests — the
        historical take. Cache on: the walk PARTITIONS into a hit group
        (artifacts in ``eng``'s prefix cache — admitted from cache, no
        prefill dispatch) and a miss group, each packing up to a full
        batch: hits don't consume miss-batch rows, so repeated traffic
        cannot fragment the misses' prefill batches (which is where the
        dispatch saving lives). Order within each group stays arrival
        order, and output is position-keyed, so bytes are unchanged."""
        bucket = self._queue[0].bucket
        hits: List[_Queued] = []
        misses: List[_Queued] = []
        rest: "collections.deque[_Queued]" = collections.deque()
        probe = self._dedup_on
        while self._queue and len(hits) < self._bs \
                and len(misses) < self._bs:
            e = self._queue.popleft()
            if e.bucket != bucket:
                rest.append(e)
                continue
            if probe and eng.cache_contains(e.digest):
                hits.append(e)
            elif self._tier is not None and self._tier.holds(e.digest):
                # the prefill tier owns this miss (docs/SERVING.md
                # "Disaggregated tiers"): hold it queued — NEVER a
                # prefill dispatch on this decode replica — until its
                # shipped artifacts land and it re-walks as a hit. The
                # tier going dead or giving the digest up flips holds()
                # false and the next walk takes the in-process path.
                rest.append(e)
            else:
                misses.append(e)
        held: List[_Queued] = []
        if probe and 0 < len(misses) < self._bs:
            # fragmentary miss group: hold it (back to the true queue
            # head, ahead of everything the walk skipped) so it packs
            # with later misses instead of wasting a prefill dispatch —
            # bounded by MISS_HOLD_ROUNDS on the group head's wait and
            # by replica idleness (a group never waits while the
            # claiming replica has nothing else to do, and rounds only
            # advance while work is in flight, so the hold can never
            # deadlock)
            busy = eng.in_flight() > 0 or eng.staged_rows > 0
            warm = bool(hits) or eng.stats.cache_hits > 0
            head_wait = self.stats.rounds - min(
                e.record.arrival_round for e in misses)
            if busy and warm and head_wait < MISS_HOLD_ROUNDS:
                held, misses = misses, []
        rest.extend(self._queue)
        self._queue = rest
        for e in reversed(held):
            self._queue.appendleft(e)
        for e in hits + misses:
            # keep the single-row payload until the request finishes: the
            # requeue source if the replica serving it retires mid-flight
            self._payloads[e.record.position] = e
        return bucket, [g for g in (hits, misses) if g]

    def _form_batch(self, bucket: int, take: List[_Queued]) -> Dict:
        """Pack the taken requests' pre-assembled rows into one batch at
        the bucket's geometry (pad rows from the cached all-pad template —
        exactly a drain-mode packed batch with serve-chosen membership)."""
        tmpl = self._templates[bucket]
        batch = {k: np.array(v) for k, v in tmpl.items()}
        positions = np.full(self._bs, -1, dtype=np.int64)
        for j, e in enumerate(take):
            for k in batch:
                batch[k][j] = e.host[k][0]
            positions[j] = e.record.position
        batch["_positions"] = positions
        if self._table is not None:
            batch["_tag"] = buckets_lib.geom_tag(self._table[bucket])
        if any(e.host is not None and "_var" in e.host for e in take):
            # per-request anonymization maps (raw-diff ingest requests,
            # docs/INGEST.md): ride the packed batch as a host-only
            # column so the emitter can de-anonymize each row's output
            vm = [(e.host.get("_var") or [None])[0] if e.host else None
                  for e in take]
            batch["_var"] = vm + [None] * (self._bs - len(take))
        if self._dedup_on:
            # forward the worker-stamped content digests so the engine's
            # cache lookup never re-hashes (host-only field, wire-stripped)
            batch["_digests"] = ([e.digest for e in take]
                                 + [None] * (self._bs - len(take)))
        return batch

    def _prefill_quarantined(self, eng: SlotEngine, batch: Dict,
                             take: List[_Queued]) -> Optional[bool]:
        """One prefill dispatch under the quarantine policy: a RAISE is a
        request problem — retried with backoff (every attempt a fresh
        fault draw), then the whole chunk shed with its error recorded; a
        WATCHDOG EXPIRY is a replica problem — the replica retires and
        the chunk requeues. Returns True (staged), False (chunk shed), or
        None (replica retired — the caller moves on)."""
        attempt = 0
        while True:
            try:
                run_with_watchdog(lambda: eng.admit(batch, 0),
                                  self._watchdog,
                                  label=f"serve_prefill[{eng.tag or 'r0'}]")
                return True
            except WatchdogTimeout as e:
                self._retire_replica(eng, e, requeue=take)
                return None
            except Exception as e:
                if attempt < self._retries:
                    attempt += 1
                    for el in take:
                        el.record.retries += 1
                    self._backoff(attempt)
                    continue
                for el in take:
                    el.record.error = (f"prefill failed after "
                                       f"{attempt + 1} attempt(s): {e}")
                    self._shed(el.record, "shed_error")
                return False

    def _retire_replica(self, eng: SlotEngine, err: BaseException, *,
                        requeue: Optional[List[_Queued]] = None) -> None:
        """Retire one replica (dispatch raised or blew the watchdog):
        drop it from the rotation and push every request it still owed —
        seated, staged, plus the caller's un-staged ``requeue`` chunk —
        back to the FRONT of the admission queue in position order (they
        arrived earliest). Their lifecycle stamps reset to 'queued'; the
        deadline clock does NOT reset (arrival_round stands), so a
        request that cannot be re-served in time is recorded-shed, never
        silently dropped. Stamps, counts, and the retired replica are
        machine-recorded in ServeStats."""
        if eng not in self.engines:
            return
        owed = set(eng.pending_positions())
        eng.retire()
        self.engines.remove(eng)
        self.stats.retirements.append(
            {"replica": eng.tag or "r0",
             "error": f"{type(err).__name__}: {err}"})
        # health record + respawn clock (robust/recovery.py): the
        # heartbeat goes cold, the alive trace steps down, and — with
        # recovery armed — the lineage's round-gated backoff starts
        hb = self.stats.heartbeats.get(eng.tag or "r0")
        if hb is not None:
            hb["alive"] = False
        if self._recovery is not None:
            self._recovery.note_retirement(
                eng, self.stats.rounds,
                error=f"{type(err).__name__}: {err}")
        self._alive_changed()
        entries: List[_Queued] = []
        seen: set = set()
        for pos in owed:
            e = self._payloads.get(pos)
            if e is not None and pos not in seen:
                seen.add(pos)
                entries.append(e)
        for e in (requeue or []):
            if e.record.position not in seen:
                seen.add(e.record.position)
                entries.append(e)
        entries.sort(key=lambda e: e.record.position)
        for e in entries:
            rec = e.record
            rec.requeues += 1
            rec.status = "queued"
            rec.admit_t = rec.seat_t = rec.first_step_t = math.nan
            # a requeued leader drags its coalesced followers back to the
            # queued milestone with it (they stay attached — re-admission
            # payloads survive dedup; the deadline clocks do not reset)
            for f in self._followers.get(rec.position, []):
                f.record.status = "queued"
                f.record.admit_t = f.record.seat_t = math.nan
                f.record.first_step_t = math.nan
        self.stats.requeues += len(entries)
        for e in reversed(entries):
            self._queue.appendleft(e)
        self._awaiting_first_step = [
            r for r in self._awaiting_first_step if r.status == "seated"]
        self._rr = self._rr % len(self.engines) if self.engines else 0

    def _shed_all_remaining(self, reason: str) -> None:
        """No live replicas: every request not yet final is shed with the
        reason recorded — the run terminates with a position-complete
        output file and an honest metrics artifact, never a hang."""
        while self._queue or self._promoted:
            e = (self._promoted.pop(0) if self._promoted
                 else self._queue.popleft())
            e.record.error = e.record.error or reason
            self._shed(e.record, "shed_error")
        # safety net: followers whose leader is neither queued nor
        # promoted (the shed->promote chain above normally drains them)
        for _leader, fl in list(self._followers.items()):
            for e in list(fl):
                if e.record.status not in ("done", "shed_queue_full",
                                           "shed_deadline", "shed_error"):
                    e.record.error = e.record.error or reason
                    self._shed(e.record, "shed_error")
        self._followers.clear()
        while self._arr_idx < len(self._times):
            item = next(self._feed_iter)
            rec = self.stats.records[self._arr_idx]
            rec.retries += int(item.retries)  # firacheck: allow[HOST-SYNC] FedBatch.retries is a host int counter stamped by the feeder worker; no device value exists here
            if item.host is not None:
                rec.ingest = item.host.get("_ingest")
            rec.error = rec.error or (str(item.error) if item.error
                                      else reason)
            self._shed(rec, "shed_error")
            self._arr_idx += 1

    def _admit(self) -> None:
        """Budgeted admission, replica round-robin: at most
        ``serve_prefill_budget`` prefill dispatches per replica between
        step dispatches. The starting replica ROTATES per round so a
        lightly loaded fleet spreads admissions instead of feeding
        replica 0 first every time (which replica serves a request never
        changes its result — the fleet's output-invariance contract —
        so rotation is purely a load-balance choice, and a
        deterministic one)."""
        admitted = 0
        admitted_pos: List[int] = []
        order = (self.engines[self._rr:] + self.engines[:self._rr])
        self._rr = (self._rr + 1) % len(self.engines) if self.engines else 0
        for eng in order:
            if eng not in self.engines:
                continue  # retired earlier in this very round
            n = 0
            retired = False
            while n < self._budget and self._queue and eng.wants_input():
                # hit/miss partition (cfg.prefix_cache): requests whose
                # prefill artifacts sit in THIS replica's cache form
                # their own chunk, admitted from cache with no prefill
                # dispatch and no budget charge (that is the latency win
                # — a cached admission never stalls the seated slots'
                # next step); misses pack a normal prefilled chunk.
                bucket, groups = self._take_chunk(eng)
                if not groups:
                    break  # a held miss group: it dispatches within
                    #        MISS_HOLD_ROUNDS once rounds advance
                for gi, group in enumerate(groups):
                    before = eng.stats.prefills
                    staged = self._prefill_quarantined(
                        eng, self._form_batch(bucket, group), group)
                    if staged is None:
                        retired = True
                        # the replica died dispatching THIS group (it was
                        # requeued by _retire_replica); any group taken
                        # off the queue but not yet dispatched must go
                        # back too, or its requests are stranded in
                        # 'queued' forever and the loop stalls
                        for g in reversed(groups[gi + 1:]):
                            for e in reversed(g):
                                self._queue.appendleft(e)
                        break
                    if not staged:
                        # chunk shed; promotions from shed leaders re-enter
                        self._drain_promotions()
                        continue
                    # the virtual clock and the latency budget charge per
                    # PREFILL DISPATCH: a cache-served or fully-coalesced
                    # admission dispatched nothing and costs neither
                    if eng.stats.prefills > before:
                        self.clock.on_prefill()
                        n += 1
                    t = self.clock.now()
                    for e in group:
                        e.record.admit_t = t
                        e.record.status = "staged"
                        admitted_pos.append(e.record.position)
                        for f in self._followers.get(e.record.position, []):
                            f.record.admit_t = t
                            f.record.status = "staged"
                            admitted_pos.append(f.record.position)
                if retired:
                    break
            admitted += n
            if eng not in self.engines:
                continue
            try:
                run_with_watchdog(lambda: eng.refill(self.refill_order),
                                  self._watchdog,
                                  label=f"serve_refill[{eng.tag or 'r0'}]")
            except Exception as e:
                self._retire_replica(eng, e)
        if self._journal is not None and admitted_pos:
            # admit WAL records: one per request, one fsync per round.
            # Resume correctness rides on the BEGIN record (stream
            # identity) + the writer crash pair; these per-request
            # records are the crash-surviving outcome/post-mortem log —
            # "never admitted" vs "admitted but unfinished" for capacity
            # analysis, shed statuses+errors that would otherwise exist
            # only in the metrics snapshot, and the progress probe the
            # kill legs poll (scripts/chaos_bench.py)
            self._journal.admit(admitted_pos)
        self.stats.admits += admitted
        self.stats.max_admits_per_round = max(
            self.stats.max_admits_per_round, admitted)
        t = self.clock.now()
        for eng in self.engines:
            for pid in eng.in_flight_positions():
                rec = self._rec_by_pos[pid]
                if math.isnan(rec.seat_t):
                    rec.seat_t = t
                    rec.status = "seated"
                    self._awaiting_first_step.append(rec)
                    # a seated leader seats its whole fan-out group: each
                    # follower keeps its own stamps but reaches the seat
                    # milestone at the same dispatch boundary
                    for f in self._followers.get(pid, []):
                        if math.isnan(f.record.seat_t):
                            f.record.seat_t = t
                            f.record.status = "seated"
                            self._awaiting_first_step.append(f.record)

    # --- health signals + self-healing (robust/recovery.py) -------------

    def _deadline_pressure(self) -> float:
        """Fraction of queued requests past HALF their deadline — the
        scale-up urgency gauge the alive trace records (0.0 with no
        deadline armed or an empty queue)."""
        if not self._deadline or not self._queue:
            return 0.0
        tight = sum(1 for e in self._queue
                    if self.stats.rounds - e.record.arrival_round
                    >= self._deadline / 2)
        return round(tight / len(self._queue), 4)

    def _alive_changed(self) -> None:
        """Append one alive-trace entry (the ROADMAP item-3 control
        signal): called at start, on every retirement, and on every
        respawn — the entries ARE the capacity-restored-over-time curve
        the recovery bench reads."""
        self.stats.replicas_alive_over_time.append({
            "round": self.stats.rounds,
            "alive": len(self.engines),
            "queue_depth": len(self._queue),
            "deadline_pressure": self._deadline_pressure(),
        })

    def _stamp_heartbeats(self) -> None:
        """Per-replica per-round heartbeat: last-dispatch round + total
        dispatches (a retired replica's stamp goes cold and its
        last-dispatch AGE grows — the health signal respawn decisions
        and post-mortems read). Recorded unconditionally, recovery armed
        or not."""
        for eng in self.engines:
            hb = self.stats.heartbeats.setdefault(
                eng.tag or "r0",
                {"last_dispatch_round": -1, "rounds": 0, "alive": True})
            hb["last_dispatch_round"] = self.stats.rounds
            hb["rounds"] += 1
            hb["alive"] = True

    def _flush_shed_log(self) -> None:
        """Flush the round's buffered shed WAL records (one fsync for
        the whole batch — see _shed)."""
        if self._journal is not None and self._shed_log:
            self._journal.append_many(self._shed_log)
            self._shed_log = []

    def _heal(self) -> None:
        """Respawn every dead lineage whose backoff elapsed and whose
        budget is not exhausted: the replacement (warm spare or fresh
        build — EngineFleet.replace_slot) attaches to the shared
        admission queue and starts pulling next round. Machine-recorded
        in ServeStats.respawns + the alive trace."""
        if self._recovery is None:
            return
        for slot in self._recovery.due(self.stats.rounds):
            attempt = slot.respawns + 1
            eng, from_spare = self._recovery.respawn(slot,
                                                     self.stats.rounds)
            if eng is None:
                continue   # builder failed: budget consumed, backoff
                #            restarted — retried or exhausted next rounds
            eng.begin_stream()
            self.engines.append(eng)
            self.stats.respawns.append({
                "replica": eng.tag or "r0", "origin": slot.origin,
                "round": self.stats.rounds, "attempt": attempt,
                "spare": from_spare})
            self._alive_changed()

    # --- the loop -------------------------------------------------------

    def run(self) -> ServeStats:
        t0 = time.perf_counter()  # firacheck: allow[WALL-CLOCK] ServeStats.wall_s is DEFINED as real elapsed seconds (the stall-fraction denominator must be wall over wall — PR 11 fourth-pass review); it never feeds the scheduling clock
        n = len(self._times)
        for eng in self.engines:
            # fresh host scheduling state per request stream (a no-op on
            # a just-constructed engine; required when a caller reuses a
            # warmed engine across serving runs — scripts/serve_bench.py)
            eng.begin_stream()
        if self._snapshot is not None:
            self._snapshot(self)   # a valid partial artifact exists from
            #                        the very first moment (kill contract)
        while self._final < n:
            self._heal()
            if not self.engines:
                if (self._recovery is not None
                        and self._recovery.can_recover()):
                    # all replicas lost but respawn budget remains: PAUSE
                    # admission (nothing dispatches) while arrivals keep
                    # queuing and deadline clocks keep ticking at their
                    # TRUE rounds — the recorded queue-depth/deadline-
                    # pressure signal stays honest through the outage —
                    # and let the round clock tick so the respawn backoff
                    # elapses: a recoverable outage, not a shed-the-
                    # remainder collapse. The budget is finite, so this
                    # loop always terminates: either a replacement
                    # attaches or can_recover goes False.
                    self._poll_arrivals(self.clock.now())
                    self._shed_deadlines()
                    self._flush_shed_log()
                    self.stats.admission_paused_rounds += 1
                    if isinstance(self.clock, WallClock):
                        # wall outage: the respawn gate is wall-time
                        # (RecoveryManager.due) and rounds are STEP
                        # DISPATCHES — nothing dispatches, so the
                        # deadline clock must not inflate with spin
                        # iterations; just wait a beat
                        time.sleep(0.01)  # firacheck: allow[SCHED-BLOCK] bounded 10ms beat on the ALL-REPLICAS-LOST pause branch: nothing can dispatch, arrivals are polled each beat, and the alternative is a busy-spin (PR 12 review)
                    else:
                        # virtual replay: the round clock IS the backoff
                        # gate — tick it deterministically
                        self.clock.on_step()
                        self.stats.rounds += 1
                    continue
                # every replica retired and no respawn budget left: shed
                # the remainder with the reason recorded —
                # position-complete output, no hang
                last = (self.stats.retirements[-1]["error"]
                        if self.stats.retirements else "unknown")
                self._shed_all_remaining(
                    f"no live replicas (all retired; last error: {last})")
                self._flush_shed_log()
                break
            self._poll_arrivals(self.clock.now())
            if self._tier is not None:
                # disaggregated prefill tier tick (serve/disagg.py):
                # sweep dead workers, deliver checksum-verified
                # artifacts into every replica's cache, submit fresh
                # misses — pure host work before admission, so this
                # round's walk can already seat freshly-landed hits
                self._tier.service(self._queue, self.engines)
            self._shed_deadlines()
            self._admit()
            live = [e for e in self.engines if e.in_flight()]
            if not live:
                if self._queue or self._promoted \
                        or any(e.staged_rows for e in self.engines):
                    if self._tier is not None \
                            and not any(e.staged_rows
                                        for e in self.engines):
                        # nothing dispatchable and the queue is waiting
                        # on the prefill tier: block briefly on the
                        # worker pipes instead of busy-spinning
                        self._tier.idle_wait(0.05)
                    continue    # seats free up / budget admits next round
                if self._arr_idx < n:
                    # idle: jump (virtual) / sleep (wall) to the next
                    # scheduled arrival — open loop, the generator never
                    # waits for us, only we for it
                    self.clock.advance_to(self._times[self._arr_idx])
                    continue
                if self._final < n:   # pragma: no cover - loop invariant
                    # a retirement always requeues into self._queue, so
                    # final < n still implies queued/staged/arriving work
                    raise RuntimeError(
                        "serve loop stalled with requests unaccounted for")
                break
            if self._dedup_on:
                # tell each replica which of its seats serve a fan-out
                # group (loop-level dedup keeps the followers up here) so
                # the engine's shared-block high-water meter covers them
                leaders = {p for p, fl in self._followers.items() if fl}
                for eng in live:
                    eng.shared_positions = leaders
            for eng in live:
                try:
                    if self._faults is not None:
                        self._faults.check("fleet.replica")
                    run_with_watchdog(eng.step_dispatch, self._watchdog,
                                      label=f"serve_step[{eng.tag or 'r0'}]")
                except Exception as e:
                    self._retire_replica(eng, e)
            self.clock.on_step()
            self.stats.rounds += 1
            self._stamp_heartbeats()
            items = []
            for eng in live:
                if eng.retired:
                    continue
                try:
                    items.extend(run_with_watchdog(
                        eng.harvest, self._watchdog,
                        label=f"serve_harvest[{eng.tag or 'r0'}]"))
                except Exception as e:
                    self._retire_replica(eng, e)
            t = self.clock.now()   # post-harvest: the honest observation
            for rec in self._awaiting_first_step:
                if rec.status == "seated":   # not requeued mid-round
                    rec.first_step_t = t
            self._awaiting_first_step = []
            done_now: List[int] = []
            for it in items:
                rec = self._rec_by_pos[it.position]
                rec.done_t = t
                rec.done_round = self.stats.rounds
                rec.status = "done"
                if self._deadline and (rec.done_round - rec.arrival_round
                                       > self._deadline):
                    rec.deadline_missed = True
                self._final += 1
                self._payloads.pop(it.position, None)
                self.stats.completions.append(it.position)
                done_now.append(it.position)
                self.emit(it.position, it.host, it.row, it.tokens, it.probs)
                # dedup fan-out delivery: the leader's settled beams are
                # byte-identical to what every coalesced follower's own
                # decode would have produced (same digest => same packed
                # payload), so each follower emits them at its OWN output
                # position with its OWN lifecycle stamps
                d = self._leader_digest.pop(it.position, None)
                if d is not None:
                    self._leaders.pop(d, None)
                group = self._followers.pop(it.position, [])
                if group:
                    self.stats.dedup_groups += 1
                    self.stats.dedup_fanout_max = max(
                        self.stats.dedup_fanout_max, 1 + len(group))
                for f in group:
                    fr = f.record
                    if math.isnan(fr.first_step_t):
                        # coalesced after the leader's first step: its
                        # first observable progress IS this harvest
                        fr.first_step_t = t
                    fr.done_t = t
                    fr.done_round = self.stats.rounds
                    fr.status = "done"
                    if self._deadline and (fr.done_round - fr.arrival_round
                                           > self._deadline):
                        fr.deadline_missed = True
                    self._final += 1
                    self.stats.completions.append(fr.position)
                    done_now.append(fr.position)
                    self.emit(fr.position, f.host, 0, it.tokens, it.probs)
            if self._journal is not None and done_now:
                # terminal WAL records AFTER the writer took the lines
                # (line-buffered — on disk): one record per request, one
                # fsync per harvest round
                self._journal.done(done_now)
            self._flush_shed_log()
            if (self._snapshot is not None
                    and self.stats.rounds % SNAPSHOT_EVERY_ROUNDS == 0):
                self._snapshot(self)
        self._flush_shed_log()   # sheds recorded after the last harvest
        self.stats.wall_s = time.perf_counter() - t0  # firacheck: allow[WALL-CLOCK] the wall_s meter's closing read — same real-wall stall-denominator contract as the t0 stamp above
        return self.stats


# --------------------------------------------------------------------------
# driver (the serving twin of decode.runner.run_test)
# --------------------------------------------------------------------------

def make_clock(clock: str, *, step_cost_s: float = 1.0,
               prefill_cost_s: float = 1.0):
    """The serve drivers' clock selector (serve_split and
    ingest.service.serve_diffs share it — one definition, no twin)."""
    if clock == "wall":
        return WallClock()
    if clock == "virtual":
        return VirtualClock(step_cost_s=step_cost_s,
                            prefill_cost_s=prefill_cost_s)
    raise ValueError(f"clock {clock!r} not in {{'wall', 'virtual'}}")


def build_engines(model, params, cfg: FiraConfig, *, engine=None,
                  engine_slots=None, guard=None, faults=None,
                  fleet_always: bool = False):
    """Engine/fleet construction shared by the serve drivers: returns
    (owner, engines, built) — ``built`` False when the caller passed a
    (presumably warm) ``engine`` whose prewarm must not rerun.
    ``fleet_always``: build an EngineFleet even at 1 replica — the
    respawn path (robust/recovery.py) needs the fleet's replace_slot /
    spare-pool surface, and a fleet-of-one is byte-identical to the bare
    engine."""
    if engine is not None:
        return engine, (getattr(engine, "engines", None) or [engine]), False
    n_rep = max(1, int(cfg.engine_replicas))
    if n_rep > 1 or fleet_always:
        from fira_tpu.parallel import fleet as fleet_lib

        owner = fleet_lib.EngineFleet(model, params, cfg, replicas=n_rep,
                                      slots=engine_slots, guard=guard,
                                      faults=faults)
        return owner, owner.engines, True
    owner = SlotEngine(model, params, cfg, slots=engine_slots,
                       guard=guard, faults=faults)
    return owner, [owner], True


def prepare_templates(owner, split, cfg: FiraConfig, table, *,
                      guard=None, prewarm: bool = True) -> Dict[int, Dict]:
    """Per-bucket all-pad templates (+ program-family prewarm when the
    driver built the engine itself): the packed-batch scaffolding both
    serve drivers share. ``split`` supplies shapes/dtypes only — the
    corpus split for graph requests, a one-row template split for
    raw-diff requests."""
    from fira_tpu.data.batching import make_batch

    bs = int(cfg.test_batch_size)
    if table is not None:
        if prewarm:
            if guard is not None:
                guard.declare(owner.labels(table))
            owner.prewarm((buckets_lib.warmup_batch(split, cfg, g, bs),
                           buckets_lib.geom_tag(g)) for g in table)
        return {b: buckets_lib.warmup_batch(split, cfg, g, bs)
                for b, g in enumerate(table)}
    templates = {0: make_batch(split, np.arange(0), cfg, batch_size=bs)}
    if prewarm:
        # unbucketed: pre-warm the single-geometry program family too
        # (prefill + no-op insert/step + harvest gather) — the dispatch
        # watchdog depends on post-warmup dispatches never paying a
        # first-use XLA compile (docs/FAULTS.md)
        owner.prewarm([(templates[0], None)])
    return templates


def run_loop_guarded(loop: "ServeLoop", snapshot) -> ServeStats:
    """Run the loop under the abort-flush contract: on ANY failure the
    freshest partial metrics snapshot survives alongside the ordered
    writer's .partial prefix (shared by both serve drivers)."""
    try:
        return loop.run()
    except BaseException:
        if snapshot is not None:
            try:
                snapshot(loop)
            except Exception:
                pass
        raise


def finalize_serve_result(stats: ServeStats, owner, faults, *,
                          out_path: str, bleu_by_pos: Dict[int, float],
                          metrics_path: Optional[str]) -> Dict:
    """The serve drivers' shared tail: split-order BLEU aggregation, the
    result dict, and the atomic final metrics artifact (+ .partial
    cleanup) — one definition so the graphs-path and diffs-path
    serve_metrics.json can never silently fork."""
    n_done = len(bleu_by_pos)
    total_bleu = sum(bleu_by_pos[p] for p in sorted(bleu_by_pos))
    result = {
        "sentence_bleu": total_bleu / max(n_done, 1),
        "n": float(n_done),
        "output_path": out_path,
        "serve": stats.summary(),
        "engine": owner.stats.summary(),
        **({"faults": faults.summary()} if faults else {}),
        "request_records": [dataclasses.asdict(r) for r in stats.records],
    }
    if metrics_path:
        write_metrics_atomic(metrics_path, {
            "serve": result["serve"],
            "engine": result["engine"],
            **({"faults": faults.summary()} if faults else {}),
            "request_records": _json_safe_records(stats.records),
        })
        if os.path.exists(metrics_path + ".partial"):
            os.remove(metrics_path + ".partial")
        result["metrics_path"] = metrics_path
    return result


def metrics_snapshotter(metrics_path: Optional[str], owner, faults):
    """The crash-contract partial-metrics hook both serve drivers pass
    to ServeLoop (None when no metrics artifact is maintained)."""
    if not metrics_path:
        return None
    partial_path = metrics_path + ".partial"
    # terminal records serialize once across the run's snapshots (see
    # _json_safe_records) — the snapshot's cost tracks the ACTIVE set,
    # not the full request count
    done_cache: Dict[int, Dict] = {}

    def snapshot(loop):
        write_metrics_atomic(partial_path, {
            "in_progress": True,
            "serve": loop.stats.summary(),
            "engine": owner.stats.summary(),
            **({"faults": faults.summary()} if faults else {}),
            "request_records": _json_safe_records(loop.stats.records,
                                                  done_cache),
        })

    return snapshot

def _request_tasks(data, cfg: FiraConfig, n: int, table, assignment,
                   mix=None):
    """One single-row ``make_batch`` task per request, request order — the
    async Feeder pre-assembles request payloads ahead of their arrival
    (an open-loop generator knows its requests up front; arrival TIME, not
    assembly, is what admission is gated on). Each task carries a ``note``
    (request position + bucket geometry) so a poisoned payload's recorded
    error names its sample.

    ``mix``: optional request->split-position map (request ``i`` serves
    sample ``mix[i]``; identity when None) — the repeated-traffic door:
    byte-identical requests at distinct output positions, which is what
    the prefix cache and the in-flight dedup exist for. With
    ``cfg.prefix_cache`` each task also stamps the payload's content
    digest WORKER-side (prefix_cache.stamp_digests), so the scheduler
    thread never pays the hashing."""
    from fira_tpu.data.batching import make_batch
    from fira_tpu.data.feeder import task_note
    from fira_tpu.decode import quant
    from fira_tpu.decode.prefix_cache import stamp_digests

    stamp = cfg.prefix_cache
    # digests carry the low-precision tier's namespace (decode/quant.py):
    # worker-side stamping and the engine's on-demand hashing both derive
    # it from the same cfg, so a cached f32 artifact never seats a bf16
    # slot and a tier change is a miss, never a wrong answer
    tier_ns = quant.tier_namespace(cfg)
    for i in range(n):
        j = int(mix[i]) if mix is not None else i  # firacheck: allow[HOST-SYNC] mix is a host request->sample index map; task generation is pure host-side planning
        geom = table[int(assignment[i])] if table is not None else None  # firacheck: allow[HOST-SYNC] host numpy bucket-assignment array — task generation is pure host-side planning
        def task(j=j, geom=geom):
            b = make_batch(data, np.asarray([j]), cfg, batch_size=1,  # firacheck: allow[HOST-SYNC] np.asarray of a host int list builds the make_batch index chunk; no device value exists here
                           geom=geom)
            return stamp_digests(b, tier_ns) if stamp else b
        task.note = task_note(
            [j], geom_tag=buckets_lib.geom_tag(geom) if geom else None,
            site="serve request")
        yield task


_TERMINAL_STATUSES = ("done", "shed_queue_full", "shed_deadline",
                      "shed_error")


def _json_safe_records(records: List[RequestRecord],
                       cache: Optional[Dict[int, Dict]] = None
                       ) -> List[Dict]:
    """Request-record dicts with NaN lifecycle stamps (shed requests were
    never seated) serialized as null — the metrics artifact is strict
    JSON (allow_nan=False).

    ``cache``: optional id(record) -> serialized-dict memo for the
    periodic snapshot path. A record in a TERMINAL status never mutates
    again, so its asdict walk (which deep-copies the per-request
    ``_ingest``/``retries`` payload) runs once instead of once per
    snapshot — without it the every-16-rounds snapshot re-serializes
    every finished request's stamps for the rest of the run, an O(n) tax
    per snapshot that profiling showed dominated by exactly this
    dataclasses.asdict + ingest-stamp rebuild."""
    out = []
    for r in records:
        if cache is not None:
            hit = cache.get(id(r))
            if hit is not None:
                out.append(hit)
                continue
        d = dataclasses.asdict(r)
        d = {k: (None if isinstance(v, float) and v != v else v)
             for k, v in d.items()}
        if cache is not None and r.status in _TERMINAL_STATUSES:
            cache[id(r)] = d
        out.append(d)
    return out


def write_metrics_atomic(path: str, payload: Dict) -> str:
    """Write a metrics artifact ATOMICALLY: full dump to ``path + ".tmp"``
    then one ``os.replace`` — a kill at any instant leaves either the
    previous complete file or the new one, never a torn JSON document
    (the OrderedStreamWriter crash discipline applied to metrics)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, allow_nan=False)
        f.flush()
        os.fsync(f.fileno())  # firacheck: allow[SCHED-BLOCK] the atomic-artifact crash contract REQUIRES the fsync before the rename (docs/FAULTS.md); it runs once per snapshot cadence (16 rounds), not per dispatch, and the cost is metered in the journal-overhead rows
    os.replace(tmp, path)
    return path


def serve_split(model: FiraModel, params, dataset: FiraDataset,
                cfg: Optional[FiraConfig] = None, *,
                arrival_times: np.ndarray,
                out_dir: str = "OUTPUT",
                ablation: Optional[str] = None,
                var_maps: Optional[List[Dict[str, str]]] = None,
                split: str = "test",
                guard=None,
                engine_slots: Optional[int] = None,
                refill_order: str = "fifo",
                clock: str = "wall",
                step_cost_s: float = 1.0,
                prefill_cost_s: float = 1.0,
                engine=None,
                faults=None,
                metrics_path: Optional[str] = None,
                request_mix=None,
                journal_path: Optional[str] = None,
                resume: bool = False) -> Dict:
    """Serve the first ``len(arrival_times)`` samples of ``split`` as an
    open-loop request stream (request ``i`` = split position ``i``,
    arriving at ``arrival_times[i]``). Writes the same position-ordered
    output file as drain-mode ``run_test`` (shed requests write an empty
    line, so the file stays position-complete; with zero sheds the bytes
    are identical to drain mode) and returns its metrics dict plus
    ``serve`` (ServeStats.summary), ``engine`` (engine/fleet stats), and
    ``request_records`` (per-request lifecycle dicts).

    ``engine``: an already-constructed (and ideally already-warmed)
    SlotEngine or EngineFleet to serve on, instead of building one —
    the bench reuses one warm engine across swept rates so the latency
    rows measure serving, not per-run cold compiles. The caller owns
    its cfg consistency (and stats resets between timed runs); the
    scheduler state itself is reset per run.

    ``faults``: an armed robust.faults.FaultInjector (None resolves from
    ``cfg.inject_faults`` — "" keeps it off at zero overhead).
    ``metrics_path``: when set, the serve metrics artifact is maintained
    THROUGH the run — a ``<path>.partial`` snapshot refreshes atomically
    every few scheduler rounds (and once on abort), and the final file
    is written atomically (tmp + rename) at completion, matching the
    ordered writer's crash contract (docs/FAULTS.md).
    ``request_mix``: optional request->split-position map (request ``i``
    serves sample ``request_mix[i]``; identity when None). Repeated
    entries are byte-identical requests at distinct output positions —
    the repeated-traffic regime the prefix cache / in-flight dedup
    (cfg.prefix_cache) exist for; the bench and chaos repeat legs drive
    exactly this.
    ``journal_path``: when set, a write-ahead request journal (one
    fsync'd JSONL record per request at admit and at done/shed —
    robust/recovery.py) is maintained next to the output, making the run
    resumable after a hard kill. ``resume``: recover a killed run —
    finished lines are read back from the journal + the ordered writer's
    crash pair and only the not-yet-done suffix is re-served; the final
    output file is byte-identical to an uninterrupted run (exactly-once
    output, docs/FAULTS.md "Recovery contracts"). Respawn (cfg
    .max_respawns / cfg.engine_spares) arms the self-healing fleet:
    retirements are followed by replacements instead of permanent
    capacity loss."""
    cfg = cfg or dataset.cfg
    if faults is None:
        faults = faults_lib.injector_from(cfg)
    data = dataset.splits[split]
    vocab = dataset.word_vocab
    indices = dataset.split_indices[split]
    times = np.asarray(arrival_times, dtype=np.float64)
    n_req = len(times)
    mix = None
    if request_mix is not None:
        mix = np.asarray(request_mix, dtype=np.int64)
        if len(mix) != n_req:
            raise ValueError(
                f"request_mix has {len(mix)} entries for {n_req} arrivals")
        if len(mix) and (mix.min() < 0 or mix.max() >= len(data)):
            raise ValueError(
                f"request_mix references split position "
                f"{int(mix.min()) if mix.min() < 0 else int(mix.max())} "
                f"outside split {split!r} (size {len(data)})")
        indices = np.asarray(indices)[mix]
    elif n_req > len(data):
        raise ValueError(
            f"arrival trace has {n_req} requests but split {split!r} holds "
            f"only {len(data)} samples")
    errs = serve_errors(cfg, trace=True)
    errs += disagg_lib.disagg_errors(cfg)
    if errs:
        raise ValueError("; ".join(errs))
    clk = make_clock(clock, step_cost_s=step_cost_s,
                     prefill_cost_s=prefill_cost_s)

    if cfg.buckets:
        table = buckets_lib.decode_table(cfg)
        ext = buckets_lib.sample_extents(data, cfg)
        assignment = buckets_lib.assign_buckets(
            ext, table, use_msg=cfg.decode_tar_buckets)
        if mix is not None:
            # request-indexed view: request i's bucket is its SAMPLE's
            assignment = np.asarray(assignment)[mix]
    else:
        table = assignment = None

    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, output_name(ablation))

    # --- crash-resume (robust/recovery.py; docs/FAULTS.md "Recovery
    # contracts"): recover every finished line of the killed run from
    # the journal + the ordered writer's crash pair, then re-serve
    # EXACTLY the not-yet-done suffix — recovered positions are
    # re-emitted verbatim, served positions are deterministic per
    # position, so the final file is byte-identical to an uninterrupted
    # run (exactly-once output). The recovery read happens BEFORE the
    # writer opens (which truncates the .partial prefix).
    from fira_tpu.robust import recovery as recovery_lib

    recovered: Dict[int, str] = {}
    remaining: Optional[np.ndarray] = None
    if resume:
        if not journal_path:
            raise recovery_lib.ResumeError(
                "resume=True requires journal_path (the write-ahead "
                "request journal of the interrupted run)")
        res_errs = recovery_lib.resume_errors(journal_path, n_req, times,
                                              mix=mix)
        if res_errs:
            raise recovery_lib.ResumeError("; ".join(res_errs))
        recovered = recovery_lib.recover_output(out_path, n_req)
        remaining = np.asarray(
            [i for i in range(n_req) if i not in recovered],
            dtype=np.int64)
        if not len(remaining):
            # everything already finished: rebuild the final file from
            # the recovered lines — no engine, no serving
            with OrderedStreamWriter(out_path, expected=n_req) as w:
                for p in sorted(recovered):
                    w.add(p, recovered[p])
            stats = ServeStats(records=[])
            stats.resumed = n_req
            result = {"sentence_bleu": 0.0, "n": 0.0,
                      "output_path": out_path, "serve": stats.summary(),
                      "engine": {}, "request_records": []}
            if metrics_path:
                write_metrics_atomic(metrics_path, {
                    "serve": result["serve"], "engine": {},
                    "request_records": []})
                if os.path.exists(metrics_path + ".partial"):
                    os.remove(metrics_path + ".partial")
                result["metrics_path"] = metrics_path
            return result

    # the serving loop's view of the stream: full on a fresh run, the
    # not-yet-done suffix (original positions kept) on a resume
    times_loop, positions, task_mix, loop_assignment = \
        times, None, mix, assignment
    if remaining is not None:
        times_loop = times[remaining]
        positions = remaining
        task_mix = mix[remaining] if mix is not None else remaining
        loop_assignment = (np.asarray(assignment)[remaining]
                           if assignment is not None else None)

    # self-healing fleet (robust/recovery.py): with a respawn budget
    # armed the engines are ALWAYS fleet-built (the fleet owns
    # replace_slot + the warm-spare pool; a fleet-of-one is
    # byte-identical to the bare engine)
    respawn_armed = cfg.max_respawns > 0
    owner, engines, built = build_engines(model, params, cfg,
                                          engine=engine,
                                          engine_slots=engine_slots,
                                          guard=guard, faults=faults,
                                          fleet_always=respawn_armed)
    templates = prepare_templates(owner, data, cfg, table, guard=guard,
                                  prewarm=built)
    recovery = None
    if respawn_armed and hasattr(owner, "replace_slot"):
        if cfg.engine_spares:
            owner.build_spares(cfg.engine_spares)
        recovery = recovery_lib.RecoveryManager(
            owner, cfg, wall_clock=(clock == "wall"))

    # disaggregated prefill tier (serve/disagg.py; docs/SERVING.md
    # "Disaggregated tiers"): spawn the worker pool AFTER the decode
    # templates exist (the workers warm the same per-bucket prefill
    # family) — each child gets the ORIGINAL f32 params as host numpy
    # (prefill always runs f32, whatever the decode tier's precision)
    tier = None
    if cfg.serve_tiers != "off":
        import jax

        params_host = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), params)
        tier = disagg_lib.PrefillTier(params_host, cfg,
                                      templates=templates, faults=faults)

    bleu_by_pos: Dict[int, float] = {}
    snapshot = metrics_snapshotter(metrics_path, owner, faults)
    journal = (recovery_lib.Journal(journal_path, n=n_req, times=times,
                                    mix=mix, resume=resume)
               if journal_path else None)

    try:
        with OrderedStreamWriter(out_path, expected=n_req) as writer, \
                Feeder(_request_tasks(data, cfg, len(times_loop), table,
                                      loop_assignment, task_mix),
                       num_workers=cfg.feeder_workers,
                       depth=cfg.feeder_depth,
                       put=False,
                       # the per-task error channel: a poisoned payload is
                       # retried in the worker, then delivered WITH its
                       # error for the loop to shed — never a consumer
                       # re-raise
                       on_error="record",
                       retries=max(0, cfg.robust_retries),
                       faults=faults) as feed:
            # resume: the recovered lines re-enter the position-keyed
            # writer first (prefix + above-gap tails both), exactly once
            for p in sorted(recovered):
                writer.add(p, recovered[p])
            emit = sample_emitter(writer, vocab=vocab, cfg=cfg,
                                  bleu_by_pos=bleu_by_pos, n_total=n_req,
                                  var_maps=var_maps, indices=indices)
            loop = ServeLoop(
                engines, cfg, arrival_times=times_loop, feed=feed,
                table=table, assignment=loop_assignment,
                templates=templates, clock=clk, emit=emit,
                # a shed request still owns its output position: an empty
                # line keeps the file position-complete and deterministic
                shed=lambda rec: writer.add(rec.position, "\n"),
                refill_order=refill_order, faults=faults,
                snapshot=snapshot, positions=positions, journal=journal,
                recovery=recovery, tier=tier)
            loop.stats.resumed = len(recovered)
            if tier is not None:
                # end-of-run counters, the ingest_cache pattern: the
                # summary closure reads the tier's final meters
                loop.stats.tiers = tier.stats.summary
            stats = run_loop_guarded(loop, snapshot)
    finally:
        if tier is not None:
            tier.close()
        if journal is not None:
            journal.close()
    # resource-lifecycle oracle (analysis.sanitizer.LeakGuard): with the
    # sanitizer armed, the run ends with every paged-block grant released
    # and every pipeline thread joined or sanctioned — a leak raises HERE
    # naming its acquire site, on the success path only (a serve error
    # must surface as itself, not be masked by its own leak fallout)
    lg = sanitizer.leak_guard()
    if lg is not None:
        lg.assert_clean("serve teardown")
    return finalize_serve_result(stats, owner, faults, out_path=out_path,
                                 bleu_by_pos=bleu_by_pos,
                                 metrics_path=metrics_path)
