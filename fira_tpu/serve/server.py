"""Arrival-timed serving loop over the slot engine (docs/SERVING.md).

The drain drivers (decode/runner.py, parallel/fleet.py) hand the engine a
pre-packed corpus stream and measure commits/s on the drained batch. This
module is the ROADMAP-item-1 other half: a long-lived SERVER under
open-loop load, where requests arrive over time (serve/arrivals.py), the
scheduler refills slots from live arrivals, and the interesting numbers
are p50/p99 TTFT and end-to-end latency against offered rate — the
Orca/vLLM serving regime, not the batch-job regime.

One scheduler round (``ServeLoop._round``), round-robined over the
engine replicas exactly like parallel/fleet.py:

1. **poll arrivals** — every request whose arrival time has passed moves
   into the admission queue (bounded by ``cfg.serve_queue_cap``; an
   arrival that finds it full is SHED immediately — rejection recorded,
   never a hang). Request payloads are pre-assembled ahead of time by the
   async Feeder (one single-row ``make_batch`` task per request, split
   order), so admission never blocks on host assembly.
2. **shed deadlines** — queued requests older than
   ``cfg.serve_deadline_steps`` step dispatches are shed (a request that
   exhausted its whole deadline without being seated cannot answer in
   time; seated requests always run to harvest and late completions are
   flagged, not killed).
3. **admit** — up to ``cfg.serve_prefill_budget`` prefill dispatches PER
   REPLICA: the head-of-queue request's bucket is flushed into one packed
   batch (up to ``test_batch_size`` same-bucket requests in arrival
   order, padded with invalid rows) and prefilled on the claiming
   replica. The budget is the latency-aware refill knob: every prefill
   dispatched here stalls the seated slots' next decode step, so a small
   budget bounds the stall seated requests pay per new admission and a
   large one trades their tail latency for admission throughput.
4. **refill / step / harvest** — the engine's own steppable pieces,
   unchanged: every live replica's step is dispatched before any harvest
   readback; harvested samples are cooked/written through the same
   position-keyed ordered writer as drain mode.

Equivalence contract (tests/test_serve.py): on a REPLAYED arrival trace
with no shedding, output file bytes are IDENTICAL to drain-mode decode —
per-sample beam math is batch-composition-invariant (every batched op is
row-wise; the contract decode/engine.py's bit-exactness tests pin), and
the writer keys by split position — and invariant to replica count,
harvest cadence, and feeder worker count, with zero post-warmup retraces
under the same declared (geometry x {prefill, step, insert, harvest})
program family: serve-mode batches reuse the drain packer's exact
geometries and batch size, so no new program ever compiles.

Clocks: ``wall`` (the bench — arrivals are paced in real time and idle
waits sleep) or ``virtual`` (replay — time advances by a fixed cost per
prefill/step dispatch and jumps across idle gaps), both observing
latencies only at dispatch/harvest boundaries, which is what the host
can honestly see.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data import buckets as buckets_lib
from fira_tpu.data.dataset import FiraDataset
from fira_tpu.data.feeder import Feeder
from fira_tpu.decode import paging
from fira_tpu.decode.engine import SlotEngine
from fira_tpu.decode.runner import output_name, sample_emitter
from fira_tpu.decode.stream import OrderedStreamWriter
from fira_tpu.model.model import FiraModel


# --------------------------------------------------------------------------
# parse-time knob validation (CLI exit 2 — the serving twin of
# parallel.mesh.divisibility_errors / decode.paging.paging_errors)
# --------------------------------------------------------------------------

def serve_errors(cfg: FiraConfig, *, trace: bool = False) -> List[str]:
    """Named-knob serving admission check. ``trace``: an arrival-trace
    file was given (the offered-rate knob is then unused)."""
    errs: List[str] = []
    if cfg.serve_rate < 0:
        errs.append(f"serve_rate {cfg.serve_rate} must be >= 0 requests/s")
    elif not trace and cfg.serve_rate == 0:
        errs.append(
            "serve_rate must be > 0 requests/s when no arrival trace is "
            "given (the open-loop Poisson generator needs an offered rate)")
    slots, _reps = paging.resolved_slots(cfg)
    if not 1 <= cfg.serve_prefill_budget <= slots:
        errs.append(
            f"serve_prefill_budget {cfg.serve_prefill_budget} must be >= 1 "
            f"and <= the per-replica engine slots ({slots}): it caps "
            f"prefill dispatches interleaved between step dispatches, and "
            f"a budget past the slot count can never seat more rows")
    if cfg.serve_deadline_steps < 0:
        errs.append(
            f"serve_deadline_steps {cfg.serve_deadline_steps} must be 0 "
            f"(no deadline) or >= 1: a request cannot complete in less "
            f"than one step dispatch")
    if cfg.serve_queue_cap < 0:
        errs.append(
            f"serve_queue_cap {cfg.serve_queue_cap} must be 0 (unbounded) "
            f"or >= 1 queued request")
    return errs


# --------------------------------------------------------------------------
# clocks
# --------------------------------------------------------------------------

class VirtualClock:
    """Deterministic replay clock: a fixed cost per prefill/step dispatch,
    idle gaps jumped. Makes a replayed trace's scheduling — hence its
    latency records — a pure function of the trace and the knobs."""

    def __init__(self, *, step_cost_s: float = 1.0,
                 prefill_cost_s: float = 1.0):
        self.step_cost_s = float(step_cost_s)
        self.prefill_cost_s = float(prefill_cost_s)
        self._now = 0.0

    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        self._now = max(self._now, float(t))

    def on_prefill(self) -> None:
        self._now += self.prefill_cost_s

    def on_step(self) -> None:
        self._now += self.step_cost_s


class WallClock:
    """Real time: arrivals are paced against the monotonic clock and an
    idle server sleeps until the next scheduled arrival (open loop — the
    generator never waits for the server, only the server for it)."""

    def __init__(self):
        self._t0 = time.perf_counter()

    def now(self) -> float:
        return time.perf_counter() - self._t0

    def advance_to(self, t: float) -> None:
        dt = float(t) - self.now()
        if dt > 0:
            time.sleep(dt)

    def on_prefill(self) -> None:
        pass

    def on_step(self) -> None:
        pass


# --------------------------------------------------------------------------
# per-request metering
# --------------------------------------------------------------------------

@dataclasses.dataclass
class RequestRecord:
    """One request's lifecycle timestamps (clock units — wall seconds or
    virtual units; every stamp is observed at a dispatch/harvest boundary,
    the only place the host honestly sees device progress)."""

    position: int            # split-local sample position
    arrival_t: float         # scheduled (open-loop) arrival time
    status: str = "pending"  # queued|staged|seated|done|shed_queue_full|
                             # shed_deadline
    arrival_round: int = -1  # step-dispatch counter at arrival (deadline base)
    admit_t: float = math.nan       # prefill dispatched (chunk staged)
    seat_t: float = math.nan        # inserted into a slot
    first_step_t: float = math.nan  # end of its first step dispatch's
                                    # harvest phase — the TTFT stamp
    done_t: float = math.nan        # harvested (all beams settled)
    done_round: int = -1
    deadline_missed: bool = False   # completed, but past its deadline

    @property
    def queue_wait_s(self) -> float:
        return self.seat_t - self.arrival_t

    @property
    def ttft_s(self) -> float:
        return self.first_step_t - self.arrival_t

    @property
    def e2e_s(self) -> float:
        return self.done_t - self.arrival_t


def _pct(values: List[float], q: float) -> Optional[float]:
    return round(float(np.percentile(np.asarray(values), q)), 6) \
        if values else None


@dataclasses.dataclass
class ServeStats:
    """Aggregate serving accounting: per-request records plus the
    scheduler counters the knee curve and the A/B rows read."""

    records: List[RequestRecord]
    completions: List[int] = dataclasses.field(default_factory=list)
    rounds: int = 0
    admits: int = 0                 # prefill batches formed from arrivals
    max_admits_per_round: int = 0   # <= serve_prefill_budget x replicas
    peak_queue_depth: int = 0
    shed_queue_full: int = 0
    shed_deadline: int = 0

    def summary(self) -> Dict:
        done = [r for r in self.records if r.status == "done"]
        ttft = [r.ttft_s for r in done if not math.isnan(r.first_step_t)]
        e2e = [r.e2e_s for r in done]
        qw = [r.queue_wait_s for r in done]
        last_done = max((r.done_t for r in done), default=0.0)
        last_arr = max((r.arrival_t for r in self.records), default=0.0)
        n = len(self.records)
        return {
            "offered": n,
            "completed": len(done),
            "shed_queue_full": self.shed_queue_full,
            "shed_deadline": self.shed_deadline,
            "deadline_missed": sum(r.deadline_missed for r in done),
            "rounds": self.rounds,
            "admits": self.admits,
            "max_admits_per_round": self.max_admits_per_round,
            "peak_queue_depth": self.peak_queue_depth,
            "offered_rate_rps": round(n / last_arr, 4) if last_arr else None,
            "makespan_s": round(last_done, 6),
            "throughput_rps": round(len(done) / last_done, 4)
            if last_done else None,
            "p50_ttft_s": _pct(ttft, 50), "p99_ttft_s": _pct(ttft, 99),
            "p50_e2e_s": _pct(e2e, 50), "p99_e2e_s": _pct(e2e, 99),
            "mean_e2e_s": round(float(np.mean(e2e)), 6) if e2e else None,
            "p50_queue_wait_s": _pct(qw, 50), "p99_queue_wait_s": _pct(qw, 99),
        }


@dataclasses.dataclass
class _Queued:
    record: RequestRecord
    host: Dict      # the request's single-row assembled batch
    bucket: int     # decode-table index (0 when unbucketed)


# --------------------------------------------------------------------------
# the serving loop
# --------------------------------------------------------------------------

class ServeLoop:
    """Drives N engine replicas under arrival-timed admission. ``emit`` /
    ``shed`` are callbacks into the output layer (the driver below wires
    them to the ordered writer)."""

    def __init__(self, engines: Sequence[SlotEngine], cfg: FiraConfig, *,
                 arrival_times: np.ndarray, feed, table, assignment,
                 templates: Dict[int, Dict], clock, emit, shed,
                 refill_order: str = "fifo"):
        self.engines = list(engines)
        self.cfg = cfg
        self.clock = clock
        self.emit = emit
        self.shed_cb = shed
        self.refill_order = refill_order
        self._table = table
        self._assignment = assignment
        self._templates = templates
        self._bs = int(cfg.test_batch_size)
        self._budget = max(1, int(cfg.serve_prefill_budget))
        self._deadline = max(0, int(cfg.serve_deadline_steps))
        self._cap = max(0, int(cfg.serve_queue_cap))
        self._times = np.asarray(arrival_times, dtype=np.float64)
        self._feed_iter = iter(feed)
        self._arr_idx = 0
        self._rr = 0   # admission round-robin start (load balance)
        self._queue: "collections.deque[_Queued]" = collections.deque()
        self._awaiting_first_step: List[RequestRecord] = []
        self._final = 0
        self.stats = ServeStats(records=[
            RequestRecord(position=i, arrival_t=float(t))
            for i, t in enumerate(self._times)])

    # --- pieces ---------------------------------------------------------

    def _poll_arrivals(self, now: float) -> None:
        """Move every due request into the admission queue; an arrival
        that finds the bounded queue full is shed on the spot."""
        while self._arr_idx < len(self._times) \
                and self._times[self._arr_idx] <= now:
            item = next(self._feed_iter)   # pre-assembled, split order
            i = self._arr_idx
            rec = self.stats.records[i]
            rec.arrival_round = self.stats.rounds
            if self._cap and len(self._queue) >= self._cap:
                self._shed(rec, "shed_queue_full")
            else:
                rec.status = "queued"
                bucket = (int(self._assignment[i])  # firacheck: allow[HOST-SYNC] host numpy bucket-assignment array (data/buckets.assign_buckets) — admission runs on host index data only, never device values
                          if self._assignment is not None else 0)
                self._queue.append(_Queued(rec, item.host, bucket))
            self._arr_idx += 1
        self.stats.peak_queue_depth = max(self.stats.peak_queue_depth,
                                          len(self._queue))

    def _shed(self, rec: RequestRecord, status: str) -> None:
        rec.status = status
        if status == "shed_queue_full":
            self.stats.shed_queue_full += 1
        else:
            self.stats.shed_deadline += 1
        self._final += 1
        self.shed_cb(rec)

    def _shed_deadlines(self) -> None:
        """Drop queued requests whose whole deadline elapsed un-seated."""
        if not self._deadline:
            return
        keep: "collections.deque[_Queued]" = collections.deque()
        for e in self._queue:
            if self.stats.rounds - e.record.arrival_round >= self._deadline:
                self._shed(e.record, "shed_deadline")
            else:
                keep.append(e)
        self._queue = keep

    def _take_chunk(self):
        """Up to ``test_batch_size`` same-bucket requests, head-of-queue's
        bucket, arrival order preserved for taken AND left-behind."""
        bucket = self._queue[0].bucket
        take: List[_Queued] = []
        rest: "collections.deque[_Queued]" = collections.deque()
        while self._queue and len(take) < self._bs:
            e = self._queue.popleft()
            (take if e.bucket == bucket else rest).append(e)
        rest.extend(self._queue)
        self._queue = rest
        return bucket, take

    def _form_batch(self, bucket: int, take: List[_Queued]) -> Dict:
        """Pack the taken requests' pre-assembled rows into one batch at
        the bucket's geometry (pad rows from the cached all-pad template —
        exactly a drain-mode packed batch with serve-chosen membership)."""
        tmpl = self._templates[bucket]
        batch = {k: np.array(v) for k, v in tmpl.items()}
        positions = np.full(self._bs, -1, dtype=np.int64)
        for j, e in enumerate(take):
            for k in batch:
                batch[k][j] = e.host[k][0]
            positions[j] = e.record.position
        batch["_positions"] = positions
        if self._table is not None:
            batch["_tag"] = buckets_lib.geom_tag(self._table[bucket])
        return batch

    def _admit(self) -> None:
        """Budgeted admission, replica round-robin: at most
        ``serve_prefill_budget`` prefill dispatches per replica between
        step dispatches. The starting replica ROTATES per round so a
        lightly loaded fleet spreads admissions instead of feeding
        replica 0 first every time (which replica serves a request never
        changes its result — the fleet's output-invariance contract —
        so rotation is purely a load-balance choice, and a
        deterministic one)."""
        admitted = 0
        order = (self.engines[self._rr:] + self.engines[:self._rr])
        self._rr = (self._rr + 1) % len(self.engines)
        for eng in order:
            n = 0
            while n < self._budget and self._queue and eng.wants_input():
                bucket, take = self._take_chunk()
                eng.admit(self._form_batch(bucket, take), 0)
                self.clock.on_prefill()
                t = self.clock.now()
                for e in take:
                    e.record.admit_t = t
                    e.record.status = "staged"
                n += 1
            admitted += n
            eng.refill(self.refill_order)
        self.stats.admits += admitted
        self.stats.max_admits_per_round = max(
            self.stats.max_admits_per_round, admitted)
        t = self.clock.now()
        for eng in self.engines:
            for pid in eng.in_flight_positions():
                rec = self.stats.records[pid]
                if math.isnan(rec.seat_t):
                    rec.seat_t = t
                    rec.status = "seated"
                    self._awaiting_first_step.append(rec)

    # --- the loop -------------------------------------------------------

    def run(self) -> ServeStats:
        n = len(self._times)
        for eng in self.engines:
            # fresh host scheduling state per request stream (a no-op on
            # a just-constructed engine; required when a caller reuses a
            # warmed engine across serving runs — scripts/serve_bench.py)
            eng.begin_stream()
        while self._final < n:
            self._poll_arrivals(self.clock.now())
            self._shed_deadlines()
            self._admit()
            live = [e for e in self.engines if e.in_flight()]
            if not live:
                if self._queue or any(e.staged_rows for e in self.engines):
                    continue    # seats free up / budget admits next round
                if self._arr_idx < n:
                    # idle: jump (virtual) / sleep (wall) to the next
                    # scheduled arrival — open loop, the generator never
                    # waits for us, only we for it
                    self.clock.advance_to(self._times[self._arr_idx])
                    continue
                if self._final < n:   # pragma: no cover - loop invariant
                    raise RuntimeError(
                        "serve loop stalled with requests unaccounted for")
                break
            for eng in live:
                eng.step_dispatch()
            self.clock.on_step()
            self.stats.rounds += 1
            items = [it for eng in live for it in eng.harvest()]
            t = self.clock.now()   # post-harvest: the honest observation
            for rec in self._awaiting_first_step:
                rec.first_step_t = t
            self._awaiting_first_step = []
            for it in items:
                rec = self.stats.records[it.position]
                rec.done_t = t
                rec.done_round = self.stats.rounds
                rec.status = "done"
                if self._deadline and (rec.done_round - rec.arrival_round
                                       > self._deadline):
                    rec.deadline_missed = True
                self._final += 1
                self.stats.completions.append(it.position)
                self.emit(it.position, it.host, it.row, it.tokens, it.probs)
        return self.stats


# --------------------------------------------------------------------------
# driver (the serving twin of decode.runner.run_test)
# --------------------------------------------------------------------------

def _request_tasks(data, cfg: FiraConfig, n: int, table, assignment):
    """One single-row ``make_batch`` task per request, split order — the
    async Feeder pre-assembles request payloads ahead of their arrival
    (an open-loop generator knows its requests up front; arrival TIME, not
    assembly, is what admission is gated on)."""
    from fira_tpu.data.batching import make_batch

    for i in range(n):
        geom = table[int(assignment[i])] if table is not None else None  # firacheck: allow[HOST-SYNC] host numpy bucket-assignment array — task generation is pure host-side planning
        yield (lambda i=i, geom=geom: make_batch(
            data, np.asarray([i]), cfg, batch_size=1, geom=geom))  # firacheck: allow[HOST-SYNC] np.asarray of a host int list builds the make_batch index chunk; no device value exists here


def serve_split(model: FiraModel, params, dataset: FiraDataset,
                cfg: Optional[FiraConfig] = None, *,
                arrival_times: np.ndarray,
                out_dir: str = "OUTPUT",
                ablation: Optional[str] = None,
                var_maps: Optional[List[Dict[str, str]]] = None,
                split: str = "test",
                guard=None,
                engine_slots: Optional[int] = None,
                refill_order: str = "fifo",
                clock: str = "wall",
                step_cost_s: float = 1.0,
                prefill_cost_s: float = 1.0,
                engine=None) -> Dict:
    """Serve the first ``len(arrival_times)`` samples of ``split`` as an
    open-loop request stream (request ``i`` = split position ``i``,
    arriving at ``arrival_times[i]``). Writes the same position-ordered
    output file as drain-mode ``run_test`` (shed requests write an empty
    line, so the file stays position-complete; with zero sheds the bytes
    are identical to drain mode) and returns its metrics dict plus
    ``serve`` (ServeStats.summary), ``engine`` (engine/fleet stats), and
    ``request_records`` (per-request lifecycle dicts).

    ``engine``: an already-constructed (and ideally already-warmed)
    SlotEngine or EngineFleet to serve on, instead of building one —
    the bench reuses one warm engine across swept rates so the latency
    rows measure serving, not per-run cold compiles. The caller owns
    its cfg consistency (and stats resets between timed runs); the
    scheduler state itself is reset per run."""
    cfg = cfg or dataset.cfg
    data = dataset.splits[split]
    vocab = dataset.word_vocab
    indices = dataset.split_indices[split]
    times = np.asarray(arrival_times, dtype=np.float64)
    n_req = len(times)
    if n_req > len(data):
        raise ValueError(
            f"arrival trace has {n_req} requests but split {split!r} holds "
            f"only {len(data)} samples")
    errs = serve_errors(cfg, trace=True)
    if errs:
        raise ValueError("; ".join(errs))
    if clock == "wall":
        clk = WallClock()
    elif clock == "virtual":
        clk = VirtualClock(step_cost_s=step_cost_s,
                           prefill_cost_s=prefill_cost_s)
    else:
        raise ValueError(f"clock {clock!r} not in {{'wall', 'virtual'}}")

    if cfg.buckets:
        table = buckets_lib.decode_table(cfg)
        ext = buckets_lib.sample_extents(data, cfg)
        assignment = buckets_lib.assign_buckets(
            ext, table, use_msg=cfg.decode_tar_buckets)
    else:
        table = assignment = None

    bs = int(cfg.test_batch_size)
    if engine is not None:
        owner = engine
        engines = getattr(owner, "engines", None) or [owner]
    else:
        n_rep = max(1, int(cfg.engine_replicas))
        if n_rep > 1:
            from fira_tpu.parallel import fleet as fleet_lib

            owner = fleet_lib.EngineFleet(model, params, cfg,
                                          replicas=n_rep,
                                          slots=engine_slots, guard=guard)
            engines = owner.engines
        else:
            owner = SlotEngine(model, params, cfg, slots=engine_slots,
                               guard=guard)
            engines = [owner]
    if table is not None:
        if engine is None:
            if guard is not None:
                guard.declare(owner.labels(table))
            owner.prewarm((buckets_lib.warmup_batch(data, cfg, g, bs),
                           buckets_lib.geom_tag(g)) for g in table)
        templates = {b: buckets_lib.warmup_batch(data, cfg, g, bs)
                     for b, g in enumerate(table)}
    else:
        from fira_tpu.data.batching import make_batch

        templates = {0: make_batch(data, np.arange(0), cfg, batch_size=bs)}

    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, output_name(ablation))
    bleu_by_pos: Dict[int, float] = {}
    with OrderedStreamWriter(out_path, expected=n_req) as writer, \
            Feeder(_request_tasks(data, cfg, n_req, table, assignment),
                   num_workers=cfg.feeder_workers, depth=cfg.feeder_depth,
                   put=False) as feed:
        emit = sample_emitter(writer, vocab=vocab, cfg=cfg,
                              bleu_by_pos=bleu_by_pos, n_total=n_req,
                              var_maps=var_maps, indices=indices)
        loop = ServeLoop(
            engines, cfg, arrival_times=times, feed=feed, table=table,
            assignment=assignment, templates=templates, clock=clk,
            emit=emit,
            # a shed request still owns its output position: an empty
            # line keeps the file position-complete and deterministic
            shed=lambda rec: writer.add(rec.position, "\n"),
            refill_order=refill_order)
        stats = loop.run()
    n_done = len(bleu_by_pos)
    total_bleu = sum(bleu_by_pos[p] for p in sorted(bleu_by_pos))
    return {
        "sentence_bleu": total_bleu / max(n_done, 1),
        "n": float(n_done),
        "output_path": out_path,
        "serve": stats.summary(),
        "engine": owner.stats.summary(),
        "request_records": [dataclasses.asdict(r) for r in stats.records],
    }
